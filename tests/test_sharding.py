"""Multi-device tests on the virtual 8-CPU mesh: the sharded train step
compiles + executes, produces the same numbers as single-device, and the
dryrun entry point works. The reference has no distributed tests at all
(SURVEY.md S4) — this is the fake-backend tier it lacked."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from alphafold2_tpu.config import Config, DataConfig, MeshConfig, ModelConfig, TrainConfig
from alphafold2_tpu.data.pipeline import SyntheticDataset
from alphafold2_tpu.parallel.sharding import make_mesh
from alphafold2_tpu.train.loop import (
    build_model,
    device_put_batch,
    init_state,
    make_train_step,
)

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 (virtual) devices"
)


def _cfg(batch_size=4):
    return Config(
        model=ModelConfig(dim=32, depth=1, heads=2, dim_head=16, max_seq_len=64,
                          bfloat16=False),
        data=DataConfig(crop_len=16, msa_depth=2, msa_len=16,
                        batch_size=batch_size, min_len_filter=8),
        train=TrainConfig(gradient_accumulate_every=1, warmup_steps=2),
    )


@pytest.mark.slow
def test_dp_sp_step_matches_single_device():
    cfg = _cfg(batch_size=4)
    batch = next(iter(SyntheticDataset(cfg.data, seed=0)))
    model = build_model(cfg)
    state = init_state(cfg, model, batch)

    # single device
    step1 = make_train_step(model, mesh=None)
    s1, m1 = step1(state, device_put_batch(batch), jax.random.key(7))

    # 4dp x 2sp mesh
    state2 = init_state(cfg, model, batch)
    mesh = make_mesh(4, 2)
    step2 = make_train_step(model, mesh=mesh)
    s2, m2 = step2(state2, device_put_batch(batch, mesh), jax.random.key(7))

    assert np.isclose(float(m1["loss"]), float(m2["loss"]), rtol=1e-4), (
        float(m1["loss"]), float(m2["loss"]),
    )
    # updated params agree across the two layouts
    for a, b in zip(jax.tree.leaves(s1.params), jax.tree.leaves(s2.params)):
        assert np.allclose(a, b, atol=1e-4)


@pytest.mark.slow
def test_ring_context_parallel_matches_dense_cross_attn():
    # model with context_parallel="ring": the trunk cross-attention runs via
    # shard_map ppermute ring; numbers must match the dense path exactly
    cfg = _cfg(batch_size=2)
    cfg2 = Config(
        model=ModelConfig(dim=32, depth=1, heads=2, dim_head=16, max_seq_len=64,
                          bfloat16=False, context_parallel="ring"),
        data=cfg.data, train=cfg.train,
    )
    batch = next(iter(SyntheticDataset(cfg.data, seed=2)))
    model_dense = build_model(cfg)
    model_ring = build_model(cfg2)
    state = init_state(cfg, model_dense, batch)

    step_dense = make_train_step(model_dense, mesh=None)
    _, m_dense = step_dense(state, device_put_batch(batch), jax.random.key(3))

    mesh = make_mesh(2, 4)
    state2 = init_state(cfg2, model_ring, batch)
    step_ring = make_train_step(model_ring, mesh=mesh)
    _, m_ring = step_ring(state2, device_put_batch(batch, mesh), jax.random.key(3))

    assert np.isclose(float(m_dense["loss"]), float(m_ring["loss"]), rtol=1e-4), (
        float(m_dense["loss"]), float(m_ring["loss"]),
    )


def test_sp_only_mesh():
    cfg = _cfg(batch_size=1)
    batch = next(iter(SyntheticDataset(cfg.data, seed=1)))
    model = build_model(cfg)
    state = init_state(cfg, model, batch)
    mesh = make_mesh(1, 8)
    step = make_train_step(model, mesh=mesh)
    state, metrics = step(state, device_put_batch(batch, mesh), jax.random.key(0))
    assert np.isfinite(float(metrics["loss"]))


@pytest.mark.slow
def test_graft_dryrun_multichip():
    import __graft_entry__

    __graft_entry__.dryrun_multichip(8)


@pytest.mark.slow
def test_msa_row_shard_tied_step_matches_single_device():
    """model.msa_row_shard=True: MSA rows sharded P(dp, sp); the tied-row
    logit contraction completes via an XLA-inserted psum over sp (SURVEY §7
    "tied-rows becomes a collective"), with numbers identical to the
    replicated single-device step."""
    cfg = Config(
        model=ModelConfig(dim=32, depth=1, heads=2, dim_head=16,
                          max_seq_len=64, bfloat16=False,
                          msa_tie_row_attn=True, msa_row_shard=True),
        data=DataConfig(crop_len=16, msa_depth=8, msa_len=16, batch_size=2,
                        min_len_filter=16),
        train=TrainConfig(gradient_accumulate_every=1, warmup_steps=2),
    )
    batch = next(iter(SyntheticDataset(cfg.data, seed=5)))
    model = build_model(cfg)

    state1 = init_state(cfg, model, batch)
    step1 = make_train_step(model, mesh=None)
    s1, m1 = step1(state1, device_put_batch(batch), jax.random.key(13))

    mesh = make_mesh(2, 4)  # 8 MSA rows over sp=4
    state2 = init_state(cfg, model, batch)
    step2 = make_train_step(model, mesh=mesh)
    s2, m2 = step2(state2, device_put_batch(batch, mesh), jax.random.key(13))

    assert np.isclose(float(m1["loss"]), float(m2["loss"]), rtol=1e-4)
    for a, b in zip(jax.tree.leaves(s1.params), jax.tree.leaves(s2.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


@pytest.mark.slow
def test_msa_row_shard_composes_with_grid_mesh():
    """msa_row_shard on a (dp, spr, spc) grid mesh: MSA rows shard over spr
    (no sp axis exists), so the tied-row psum composes with 2D pair-grid
    sharding instead of silently replicating. Numbers == single device."""
    from alphafold2_tpu.parallel.grid_parallel import make_grid_mesh

    cfg = Config(
        model=ModelConfig(dim=32, depth=1, heads=2, dim_head=16,
                          max_seq_len=64, bfloat16=False,
                          msa_tie_row_attn=True, msa_row_shard=True,
                          grid_parallel=True),
        mesh=MeshConfig(data_parallel=2, grid_rows=2, grid_cols=2),
        data=DataConfig(crop_len=16, msa_depth=4, msa_len=16, batch_size=2,
                        min_len_filter=16),
        train=TrainConfig(gradient_accumulate_every=1, warmup_steps=2),
    )
    batch = next(iter(SyntheticDataset(cfg.data, seed=6)))
    model = build_model(cfg)

    state1 = init_state(cfg, model, batch)
    step1 = make_train_step(model, mesh=None)
    s1, m1 = step1(state1, device_put_batch(batch), jax.random.key(17))

    mesh = make_grid_mesh(2, 2, 2)  # 4 MSA rows over spr=2
    state2 = init_state(cfg, model, batch)
    step2 = make_train_step(model, mesh=mesh)
    s2, m2 = step2(state2, device_put_batch(batch, mesh), jax.random.key(17))

    assert np.isclose(float(m1["loss"]), float(m2["loss"]), rtol=1e-4)
    for a, b in zip(jax.tree.leaves(s1.params), jax.tree.leaves(s2.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)
