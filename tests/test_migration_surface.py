"""MIGRATION.md anti-rot test: every symbol and calling pattern the
migration guide promises to reference users must exist and run. Mirrors the
reference's README usage (README.md:15-49) through this framework's API."""

import jax
import jax.numpy as jnp
import numpy as np


def test_reference_readme_usage_pattern():
    """The reference's front-page snippet, translated per MIGRATION.md:
    model -> distogram -> center_distogram -> MDScaling -> 3D coords."""
    from alphafold2_tpu.models import Alphafold2
    from alphafold2_tpu.utils.mds import MDScaling
    from alphafold2_tpu.utils.structure import center_distogram

    model = Alphafold2(dim=32, depth=1, heads=2, dim_head=16, max_seq_len=64,
                       use_flash=False)
    k = jax.random.key(0)
    seq = jax.random.randint(jax.random.fold_in(k, 1), (1, 16), 0, 21)
    msa = jax.random.randint(jax.random.fold_in(k, 2), (1, 3, 16), 0, 21)
    mask = jnp.ones((1, 16), bool)
    msa_mask = jnp.ones((1, 3, 16), bool)
    params = model.init(k, seq, msa, mask=mask, msa_mask=msa_mask)
    distogram = model.apply(params, seq, msa, mask=mask, msa_mask=msa_mask)
    assert distogram.shape == (1, 16, 16, 37)  # reference output spec

    probs = jax.nn.softmax(distogram, -1)
    distances, weights = center_distogram(probs)
    coords_3d, _ = MDScaling(distances, weights=weights, iters=10,
                             fix_mirror=0)
    assert coords_3d.shape == (1, 3, 16)
    assert np.isfinite(np.asarray(coords_3d)).all()


def test_migration_symbols_exist():
    """Every API name the migration table maps must import."""
    from alphafold2_tpu.models import Alphafold2  # noqa: F401
    from alphafold2_tpu.ops.sparse import BlockSparseConfig  # noqa: F401
    from alphafold2_tpu.parallel.seq_parallel import (  # noqa: F401
        tied_row_attention,
    )
    from alphafold2_tpu.utils.mds import MDScaling, mds  # noqa: F401
    from alphafold2_tpu.utils.metrics import (  # noqa: F401
        GDT, RMSD, Kabsch, TMscore, calc_phis, get_dihedral,
    )
    from alphafold2_tpu.utils.pdb import (  # noqa: F401
        backbone_to_pdb, clean_pdb, custom2pdb, download_pdb,
    )
    from alphafold2_tpu.utils.structure import (  # noqa: F401
        center_distogram, get_bucketed_distance_matrix, scn_backbone_mask,
        scn_cloud_mask, sidechain_container,
    )

    # ctor kwargs promised to carry over from the reference
    import inspect

    fields = set(inspect.signature(Alphafold2).parameters)
    for kw in ("dim", "depth", "heads", "dim_head", "max_seq_len",
               "reversible", "sparse_self_attn", "cross_attn_compress_ratio",
               "msa_tie_row_attn", "template_attn_depth", "attn_dropout",
               "ff_dropout"):
        assert kw in fields, kw
