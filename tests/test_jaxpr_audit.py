"""Jaxpr auditor tests: every rule must fire on a deliberately-poisoned
function (f64 widening, host callbacks — including inside scan bodies —
giant baked-in constants, dead donation, implicit promotion) and stay
silent on clean graphs; waivers must be reasoned; and the real registered
targets must audit clean (slow tier — CI runs the CLI equivalent)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from alphafold2_tpu.analysis import jaxpr_audit
from alphafold2_tpu.analysis.targets import TraceTarget, default_targets


def synthetic(name, fn, args, donate=(), allow=frozenset(), reasons=None):
    return TraceTarget(
        name=name, build=lambda: (fn, args), donate_argnums=donate,
        allow=allow, allow_reasons=reasons,
    )


def rules_of(findings):
    return sorted({f.rule for f in findings})


# ------------------------------------------------------------- jaxpr rules


def test_clean_function_has_no_findings():
    t = synthetic("clean", lambda x: x * 2.0 + 1.0, (jnp.ones((4,)),))
    assert jaxpr_audit.audit_target(t) == []


def test_f64_widening_rejected():
    with jax.experimental.enable_x64():

        def poisoned(x):
            return x.astype(jnp.float64) * 2.0

        t = synthetic(
            "f64", poisoned, (jnp.ones((4,), jnp.float32),)
        )
        findings = jaxpr_audit.audit_target(t)
    assert "AF2A101" in rules_of(findings), findings
    assert any("float64" in f.message for f in findings)


def test_host_callback_rejected():
    def poisoned(x):
        return jax.pure_callback(
            lambda v: np.sin(v), jax.ShapeDtypeStruct(x.shape, x.dtype), x
        )

    t = synthetic("cb", poisoned, (jnp.ones((4,)),))
    findings = jaxpr_audit.audit_target(t)
    assert rules_of(findings) == ["AF2A102"], findings


def test_host_callback_found_inside_scan_body():
    """The traversal must recurse into control-flow sub-jaxprs."""

    def poisoned(xs):
        def body(carry, x):
            y = jax.pure_callback(
                lambda v: np.abs(v), jax.ShapeDtypeStruct((), xs.dtype), x
            )
            return carry + y, y

        total, _ = jax.lax.scan(body, jnp.zeros(()), xs)
        return total

    t = synthetic("cb_scan", poisoned, (jnp.ones((8,)),))
    assert "AF2A102" in rules_of(jaxpr_audit.audit_target(t))


def test_giant_baked_constant_rejected():
    big = jnp.zeros((600, 600), jnp.float32)  # 1.44 MB closed over

    def poisoned(x):
        return x + big[0, 0]

    t = synthetic("const", poisoned, (jnp.ones(()),))
    findings = jaxpr_audit.audit_target(t)
    assert rules_of(findings) == ["AF2A103"], findings
    # raising the threshold clears it
    assert jaxpr_audit.audit_target(t, const_threshold=2 << 20) == []


def test_dead_donation_flagged_and_waivable():
    def fwd(tokens):
        return tokens.astype(jnp.float32) * 2.0

    args = (jnp.zeros((8,), jnp.int32),)
    t = synthetic("donate", fwd, args, donate=(0,))
    findings = jaxpr_audit.audit_target(t)
    assert rules_of(findings) == ["AF2A104"], findings

    waived = synthetic(
        "donate", fwd, args, donate=(0,),
        allow=frozenset({"AF2A104"}),
        reasons={"AF2A104": "int buffers intentionally freed early"},
    )
    assert jaxpr_audit.audit_target(waived) == []


def test_matching_donation_is_clean():
    t = synthetic(
        "donate_ok", lambda x: x * 2.0, (jnp.ones((8,)),), donate=(0,)
    )
    assert jaxpr_audit.audit_target(t) == []


def test_strict_promotion_violation_flagged():
    def poisoned(m, x):
        return m * x  # bool * f32: implicit promotion

    t = synthetic(
        "promo", poisoned, (jnp.ones((4,), bool), jnp.ones((4,)))
    )
    findings = jaxpr_audit.audit_target(t)
    assert rules_of(findings) == ["AF2A105"], findings


def test_build_failure_is_a_finding():
    def exploding_build():
        raise RuntimeError("no such checkpoint")

    t = TraceTarget(name="broken", build=exploding_build)
    findings = jaxpr_audit.audit_target(t)
    assert rules_of(findings) == ["AF2A100"]
    assert "no such checkpoint" in findings[0].message


def test_waiver_without_reason_is_rejected():
    with pytest.raises(ValueError, match="without a reason"):
        TraceTarget(
            name="bad", build=lambda: (lambda x: x, (jnp.ones(()),)),
            allow=frozenset({"AF2A104"}),
        )


# ------------------------------------------------ deep sub-jaxpr recursion


def test_callback_buried_in_custom_vjp_bwd():
    """The violation hides in the custom_vjp *backward* body — reachable
    only through the fwd/bwd thunks iter_eqns_deep unpacks, never through
    the plain forward trace."""

    @jax.custom_vjp
    def f(x):
        return x * 2.0

    def f_fwd(x):
        return f(x), x

    def f_bwd(res, g):
        jax.debug.callback(lambda v: None, res)
        return (g * 2.0,)

    f.defvjp(f_fwd, f_bwd)

    t = synthetic("vjp", lambda x: f(x).sum(), (jnp.ones((4,)),))
    assert "AF2A102" in rules_of(jaxpr_audit.audit_target(t))


def test_callback_buried_in_custom_jvp_rule():
    @jax.custom_jvp
    def g(x):
        return x * 2.0

    @g.defjvp
    def g_jvp(primals, tangents):
        (x,), (t,) = primals, tangents
        jax.debug.callback(lambda v: None, x)
        return g(x), t * 2.0

    t = synthetic("jvp", lambda x: g(x).sum(), (jnp.ones((4,)),))
    assert "AF2A102" in rules_of(jaxpr_audit.audit_target(t))


def test_callback_buried_in_nested_jit():
    inner = jax.jit(
        lambda x: jax.pure_callback(
            lambda v: np.sin(v), jax.ShapeDtypeStruct((4,), jnp.float32), x
        )
    )
    t = synthetic("pjit", lambda x: inner(x) + 1.0, (jnp.ones((4,)),))
    assert "AF2A102" in rules_of(jaxpr_audit.audit_target(t))


def test_clean_custom_vjp_recursion_terminates():
    """The standard fwd-calls-f pattern re-embeds the custom_vjp_call in
    its own forward body; the signature seen-guard must terminate the walk
    and report nothing."""

    @jax.custom_vjp
    def f(x):
        return x * 2.0

    def f_fwd(x):
        return f(x), x

    def f_bwd(res, g):
        return (g * 2.0,)

    f.defvjp(f_fwd, f_bwd)
    t = synthetic("vjp_ok", lambda x: f(x).sum(), (jnp.ones((4,)),))
    assert jaxpr_audit.audit_target(t) == []


# ---------------------------------------------------------- real targets


@pytest.mark.slow
def test_registered_targets_audit_clean():
    """The shipped model/train/serve executables carry no findings — the
    CI jaxpr-audit job's in-suite twin."""
    findings = jaxpr_audit.audit(default_targets())
    assert findings == [], "\n".join(f.format() for f in findings)


# ------------------------------------------------------- lowering fold-in


def test_lowering_gate_refusal_surfaces_as_finding():
    """A gate run that certifies nothing (typo'd case name) must produce a
    finding, never silent green."""
    findings = jaxpr_audit.lowering_findings(["no_such_case"])
    assert rules_of(findings) == ["AF2A106"]
    assert "unknown case" in findings[0].message


@pytest.mark.slow
def test_lowering_negative_control_folds_in_clean():
    """The gate's own negative control passes through the auditor's
    findings stream with zero findings (the mis-tiled kernel is rejected,
    which is the case SUCCEEDING)."""
    findings = jaxpr_audit.lowering_findings(
        ["negative_control_rejects_bad_tiling"]
    )
    assert findings == [], [f.format() for f in findings]
