"""bf16 serving mode: per-layer drift vs f32 pinned against stated bounds.

The bf16 serving mode (ServeConfig.dtype="bfloat16") is numerically GATED,
not asserted: the in-graph numerics tags (observe/numerics.py — embeddings,
every trunk layer boundary, the distogram logits) are collected for the
same tiny trunk at f32 and bf16, and the per-tensor drift must stay inside
the bounds below. The bounds are the contract README documents; measured
drift on this config sits ~10x under them (per-layer norm drift <= 7e-4,
logits relative error ~0.9%), so a violation means the bf16 path changed,
not that the tolerance was tight.

Coordinate-level parity is deliberately NOT asserted: structure realization
chaotically amplifies trunk-level perturbations (pinned by the attribution
test in tests/test_serve_mesh.py), so the honest bf16 contract is at the
trunk/logits level plus end-to-end finiteness and serving health.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from alphafold2_tpu.models.alphafold2 import Alphafold2
from alphafold2_tpu.observe import numerics

# The stated bf16 drift bounds (README "Pallas kernels & low-precision
# serving"): relative drift of each tagged tensor's L2 norm, and relative
# L2 error of the distogram logits vs the f32 run. Re-baselining policy:
# loosen ONLY with a PR that explains the numerical change.
PER_LAYER_L2_DRIFT_BOUND = 0.01
LOGITS_REL_ERR_BOUND = 0.05


def _tiny_trunk(dtype):
    return Alphafold2(
        dim=32, depth=2, heads=2, dim_head=16, max_seq_len=64,
        msa_tie_row_attn=True, dtype=dtype,
    )


def _inputs():
    rng = np.random.default_rng(0)
    b, n, m, nm = 1, 24, 4, 24
    seq = jnp.asarray(rng.integers(0, 20, (b, n)), jnp.int32)
    msa = jnp.asarray(rng.integers(0, 20, (b, m, nm)), jnp.int32)
    mask = jnp.ones((b, n), bool).at[:, 20:].set(False)
    msa_mask = jnp.ones((b, m, nm), bool).at[:, :, 20:].set(False)
    return seq, msa, mask, msa_mask


def _cast_bf16(params):
    return jax.tree.map(
        lambda x: x.astype(jnp.bfloat16)
        if getattr(x, "dtype", None) == jnp.float32 else x,
        params,
    )


def _run_tagged(model, params, seq, msa, mask, msa_mask):
    with numerics.collect() as col:
        logits = model.apply(
            params, seq, msa, mask=mask, msa_mask=msa_mask,
            deterministic=True,
        )
    return np.asarray(logits, np.float32), numerics.stats_to_host(
        col.stats()
    )


@pytest.fixture(scope="module")
def drift():
    seq, msa, mask, msa_mask = _inputs()
    f32 = _tiny_trunk(jnp.float32)
    params = f32.init(jax.random.key(0), seq, msa, mask=mask,
                      msa_mask=msa_mask)
    logits_f, stats_f = _run_tagged(f32, params, seq, msa, mask, msa_mask)
    bf16 = _tiny_trunk(jnp.bfloat16)
    logits_b, stats_b = _run_tagged(
        bf16, _cast_bf16(params), seq, msa, mask, msa_mask
    )
    return logits_f, stats_f, logits_b, stats_b


def test_bf16_per_layer_drift_inside_bounds(drift):
    _, stats_f, _, stats_b = drift
    shared = set(stats_f) & set(stats_b)
    # the tag vocabulary itself must not silently shrink: every layer
    # boundary the f32 trunk tags must exist in the bf16 run too
    assert shared == set(stats_f), (set(stats_f) ^ set(stats_b))
    assert any(name.startswith("trunk.layer_") for name in shared)
    for name in sorted(shared):
        a, b = stats_f[name], stats_b[name]
        rel = abs(b["l2"] - a["l2"]) / max(a["l2"], 1e-9)
        assert rel <= PER_LAYER_L2_DRIFT_BOUND, (
            f"{name}: bf16 L2 drift {rel:.4f} exceeds the stated bound "
            f"{PER_LAYER_L2_DRIFT_BOUND}"
        )


def test_bf16_introduces_no_nonfinites(drift):
    _, _, _, stats_b = drift
    for name, s in stats_b.items():
        assert s["nan_count"] == 0 and s["inf_count"] == 0, (name, s)
    assert numerics.first_nonfinite(stats_b) is None


def test_bf16_logits_error_inside_bounds(drift):
    logits_f, _, logits_b, _ = drift
    rel = np.linalg.norm(logits_b - logits_f) / max(
        np.linalg.norm(logits_f), 1e-9
    )
    assert rel <= LOGITS_REL_ERR_BOUND, (
        f"distogram logits rel L2 error {rel:.4f} exceeds the stated "
        f"bound {LOGITS_REL_ERR_BOUND}"
    )
    # and the drift is REAL (the two runs are not accidentally identical,
    # which would mean the bf16 cast silently did not happen)
    assert rel > 0


def test_bf16_serve_engine_end_to_end():
    """ServeEngine in the bf16 mode + fused tied-row kernel policy: params
    actually cast, requests served ok with finite coords, and the
    executable identity (compile records) carries the dtype+kernel keys
    the regression gate refuses to cross-compare."""
    from alphafold2_tpu.config import (
        Config, DataConfig, ModelConfig, ServeConfig,
    )
    from alphafold2_tpu.serve import ServeEngine

    cfg = Config(
        model=ModelConfig(
            dim=32, depth=1, heads=2, dim_head=16, max_seq_len=48,
            bfloat16=False, msa_tie_row_attn=True,
        ),
        data=DataConfig(msa_depth=2),
        serve=ServeConfig(
            buckets=(8, 16), max_batch=2, mds_iters=8,
            dtype="bfloat16", kernels="tied_row=pallas",
        ),
    )
    engine = ServeEngine(cfg)
    assert engine.serve_dtype == "bfloat16"
    assert engine.kernels_desc == "tied_row=pallas"
    float_leaves = [
        x for x in jax.tree.leaves(engine.params)
        if jnp.issubdtype(x.dtype, jnp.floating)
    ]
    assert float_leaves and all(
        x.dtype == jnp.bfloat16 for x in float_leaves
    )
    results = engine.predict_many(["ACDEFGH", "MKVLAWGACDEF"])
    for r in results:
        assert r.ok, r
        assert np.all(np.isfinite(r.atom14))
    for rec in engine.compile_records:
        assert rec["dtype"] == "bfloat16"
        assert rec["kernels"] == "tied_row=pallas"
        assert rec["flops_breakdown"]["tied_row"] > 0


def test_serve_dtype_validation():
    from alphafold2_tpu.config import Config, ServeConfig
    from alphafold2_tpu.serve import ServeEngine

    cfg = Config(serve=ServeConfig(buckets=(8,), dtype="float16"))
    with pytest.raises(ValueError, match="serve.dtype"):
        ServeEngine(cfg)
