"""gelu_exact knob: the reference's torch F.gelu is the exact erf form
(alphafold2.py:57); jax defaults to the tanh approximation (kept as this
framework's TPU-first default). The flag must actually switch the function
everywhere a FeedForward runs, and the exact form must match torch."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from alphafold2_tpu.config import Config, DataConfig, ModelConfig
from alphafold2_tpu.ops.attention import FeedForward
from alphafold2_tpu.train.loop import build_model, tiny_init_state


def test_exact_gelu_matches_torch():
    torch = pytest.importorskip("torch")
    x = np.linspace(-4, 4, 201, dtype=np.float32)
    ours = np.asarray(jax.nn.gelu(jnp.asarray(x), approximate=False))
    theirs = torch.nn.functional.gelu(torch.tensor(x)).numpy()
    np.testing.assert_allclose(ours, theirs, atol=1e-6)
    # and the tanh form differs measurably — the knob is not a no-op
    approx = np.asarray(jax.nn.gelu(jnp.asarray(x), approximate=True))
    assert np.abs(approx - theirs).max() > 1e-4


def test_feedforward_flag_switches_output():
    x = jax.random.normal(jax.random.key(0), (2, 8, 16))
    ff_a = FeedForward(dim=16, gelu_exact=False)
    ff_e = FeedForward(dim=16, gelu_exact=True)
    params = ff_a.init(jax.random.key(1), x)  # same params both ways
    out_a = ff_a.apply(params, x)
    out_e = ff_e.apply(params, x)
    assert not np.allclose(np.asarray(out_a), np.asarray(out_e))


@pytest.mark.parametrize("engine", ["default", "reversible"])
def test_model_level_flag_reaches_trunk(engine):
    kw = dict(dim=32, depth=1, heads=2, dim_head=16, max_seq_len=64,
              bfloat16=False, reversible=engine == "reversible")
    cfg_a = Config(model=ModelConfig(**kw),
                   data=DataConfig(crop_len=16, msa_depth=2, msa_len=16,
                                   batch_size=1))
    cfg_e = Config(model=ModelConfig(**kw, gelu_exact=True),
                   data=cfg_a.data)
    model_a, model_e = build_model(cfg_a), build_model(cfg_e)
    state = tiny_init_state(cfg_a, model_a)

    seq = jax.random.randint(jax.random.key(2), (1, 16), 0, 21)
    msa = jax.random.randint(jax.random.key(3), (1, 2, 16), 0, 21)
    mask = jnp.ones((1, 16), bool)
    msa_mask = jnp.ones((1, 2, 16), bool)
    out_a = model_a.apply(state.params, seq, msa, mask=mask, msa_mask=msa_mask)
    out_e = model_e.apply(state.params, seq, msa, mask=mask, msa_mask=msa_mask)
    assert not np.allclose(np.asarray(out_a), np.asarray(out_e))
    assert np.abs(np.asarray(out_a) - np.asarray(out_e)).max() < 0.1
