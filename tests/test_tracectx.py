"""Trace-context unit tests (observe/tracectx.py + tracing.py): context
minting and W3C traceparent round-trips, thread-local propagation with
explicit handoff, Tracer auto-attach child minting, the lenient trace
loader, and reconstruction/completeness over synthetic lifecycles."""

import json
import threading

import pytest

from alphafold2_tpu.observe.tracectx import (
    DEDUP_EVENT,
    RESOLVE_EVENT,
    SUBMIT_EVENT,
    TraceContext,
    current_trace,
    reconstruct_traces,
    trace_completeness,
    trace_incomplete_reason,
    use_trace,
)
from alphafold2_tpu.observe.tracing import (
    Tracer,
    load_trace_events_lenient,
)


# ------------------------------------------------------------ context core


def test_new_context_shape_and_child_chain():
    ctx = TraceContext.new()
    assert len(ctx.trace_id) == 32 and len(ctx.span_id) == 16
    assert ctx.parent_id is None
    child = ctx.child()
    assert child.trace_id == ctx.trace_id  # same request
    assert child.parent_id == ctx.span_id  # chained to the minter
    assert child.span_id != ctx.span_id
    grand = child.child()
    assert grand.parent_id == child.span_id


def test_traceparent_round_trip_and_validation():
    ctx = TraceContext.new()
    header = ctx.traceparent()
    assert header == f"00-{ctx.trace_id}-{ctx.span_id}-01"
    back = TraceContext.from_traceparent(header)
    assert back.trace_id == ctx.trace_id and back.span_id == ctx.span_id
    for bad in ("", "00-zz-xx-01", "00-abc-def", "01-" + "0" * 49):
        with pytest.raises(ValueError):
            TraceContext.from_traceparent(bad)


def test_event_args_omit_unset_parent():
    ctx = TraceContext.new()
    assert "parent_id" not in ctx.event_args()
    assert "parent_id" in ctx.child().event_args()


def test_use_trace_is_thread_local():
    ctx = TraceContext.new()
    seen = {}

    def worker():
        seen["other_thread"] = current_trace()

    with use_trace(ctx):
        assert current_trace() is ctx
        t = threading.Thread(target=worker)
        t.start()
        t.join()
        # nested handoff restores the outer context on exit
        inner = ctx.child()
        with use_trace(inner):
            assert current_trace() is inner
        assert current_trace() is ctx
    assert current_trace() is None
    assert seen["other_thread"] is None  # no cross-thread leak


# ----------------------------------------------------- tracer auto-attach


def test_tracer_span_mints_child_under_active_context():
    tracer = Tracer(enabled=True)
    ctx = TraceContext.new()
    with use_trace(ctx):
        with tracer.span("outer"):
            inner = current_trace()
            assert inner is not None and inner.trace_id == ctx.trace_id
            assert inner.parent_id == ctx.span_id
            tracer.instant("mark")  # instants attach, don't mint
    events = {e["name"]: e for e in tracer.events()}
    assert events["outer"]["args"]["trace_id"] == ctx.trace_id
    assert events["outer"]["args"]["parent_id"] == ctx.span_id
    # the instant attaches the active (minted) context rather than minting
    # its own child: it reports from inside the span
    assert events["mark"]["args"]["span_id"] == inner.span_id
    assert events["mark"]["args"]["parent_id"] == ctx.span_id


def test_tracer_span_without_context_stays_unattached():
    tracer = Tracer(enabled=True)
    with tracer.span("orphan"):
        pass
    (event,) = tracer.events()
    assert "trace_id" not in event.get("args", {})


# --------------------------------------------------------- lenient loading


def test_lenient_loader_reports_lines_and_keeps_good_events(tmp_path):
    path = tmp_path / "trace.json"
    path.write_text(
        "[\n"
        '{"name": "a", "ph": "X", "ts": 1, "dur": 2},\n'
        '{"name": "b", "ph": "X", "ts":\n'  # truncated mid-write
        "17\n"  # parses but is not an event object
        '{"name": "c", "ph": "i", "ts": 5},\n'
        "]\n"
    )
    events, errors = load_trace_events_lenient(str(path))
    assert [e["name"] for e in events] == ["a", "c"]
    assert len(errors) == 2
    assert any("line 3" in e for e in errors)


def test_lenient_loader_accepts_wellformed_array(tmp_path):
    path = tmp_path / "trace.json"
    path.write_text(json.dumps([{"name": "a", "ph": "X", "ts": 0}]))
    events, errors = load_trace_events_lenient(str(path))
    assert [e["name"] for e in events] == ["a"] and errors == []


# ----------------------------------------- reconstruction and completeness


def _lifecycle(ctx, *, resolve=True, dispatch=True, cached=False):
    """Synthetic event list for one request trace."""
    ev = [{"name": SUBMIT_EVENT, "ph": "i", "ts": 0,
           "args": ctx.event_args()}]
    child = ctx.child()
    if cached:
        ev.append({"name": "sched.cache_hit", "ph": "i", "ts": 1,
                   "args": child.event_args()})
    elif dispatch:
        ev.append({"name": "sched.dispatch", "ph": "X", "ts": 1, "dur": 5,
                   "args": {"trace_ids": [ctx.trace_id]}})
    if resolve:
        # chained to the ROOT span, flags included — as the scheduler emits
        ev.append({"name": RESOLVE_EVENT, "ph": "i", "ts": 9,
                   "args": {"status": "ok", "cache_hit": cached,
                            **ctx.child().event_args()}})
    return ev


def test_reconstruct_groups_owned_and_shared_events():
    a, b = TraceContext.new(), TraceContext.new()
    events = _lifecycle(a) + _lifecycle(b)
    traces = reconstruct_traces(events)
    assert set(traces) == {a.trace_id, b.trace_id}
    # the shared dispatch span lands in its member's trace
    assert any(e["name"] == "sched.dispatch" for e in traces[a.trace_id])


def test_completeness_verdicts():
    ok = TraceContext.new()
    cached = TraceContext.new()
    no_resolve = TraceContext.new()
    no_dispatch = TraceContext.new()
    events = (
        _lifecycle(ok)
        + _lifecycle(cached, cached=True)
        + _lifecycle(no_resolve, resolve=False)
        + _lifecycle(no_dispatch, dispatch=False)
    )
    traces = reconstruct_traces(events)
    assert trace_incomplete_reason(ok.trace_id, traces[ok.trace_id]) is None
    assert trace_incomplete_reason(
        cached.trace_id, traces[cached.trace_id]) is None
    assert "resolve" in trace_incomplete_reason(
        no_resolve.trace_id, traces[no_resolve.trace_id])
    assert trace_incomplete_reason(
        no_dispatch.trace_id, traces[no_dispatch.trace_id]) is not None

    summary = trace_completeness(
        events,
        [ok.trace_id, cached.trace_id, no_resolve.trace_id,
         no_dispatch.trace_id],
    )
    assert summary["total"] == 4 and summary["complete"] == 2
    assert summary["fraction"] == 0.5
    assert len(summary["incomplete"]) == 2


def test_completeness_empty_is_vacuously_complete():
    assert trace_completeness([], [])["fraction"] == 1.0


def test_broken_parent_chain_is_incomplete():
    ctx = TraceContext.new()
    stranger = TraceContext.new()
    events = _lifecycle(ctx)
    # an event claiming a parent span that no event in this trace owns
    events.append({
        "name": "sched.queue", "ph": "X", "ts": 2, "dur": 1,
        "args": {"trace_id": ctx.trace_id, "span_id": "feedfacefeedface",
                 "parent_id": stranger.span_id},
    })
    traces = reconstruct_traces(events)
    reason = trace_incomplete_reason(ctx.trace_id, traces[ctx.trace_id])
    assert reason is not None and "parent" in reason


def test_dedup_event_constant_exported():
    # the scheduler's follower join event is part of the completeness
    # contract; pin the name the reconstruction logic greps for
    assert DEDUP_EVENT == "sched.dedup_join"
