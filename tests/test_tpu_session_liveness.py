"""Unit tests for the session driver's backend-liveness hardening
(VERDICT r4 #1b): after a backend death signature, every jax stage gets a
cheap subprocess probe instead of betting a 1500-2400s stage deadline on a
dead tunnel — round 4 burned ~1.5h of its only window exactly that way.
"""

import importlib
import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture()
def session(monkeypatch, tmp_path):
    """Import scripts/tpu_session.py with its artifact redirected to tmp —
    the module must never clobber the repo's committed TPU_SESSION.json
    from a test run."""
    monkeypatch.setenv("AF2TPU_SESSION_OUT", str(tmp_path / "out.json"))
    monkeypatch.setenv("AF2TPU_PLATFORM", "cpu")
    monkeypatch.syspath_prepend(os.path.join(REPO, "scripts"))
    sys.modules.pop("tpu_session", None)
    mod = importlib.import_module("tpu_session")
    yield mod
    sys.modules.pop("tpu_session", None)


def test_death_signature_marks_backend_suspect(session):
    session.RESULTS["stages"]["profile"] = {
        "ok": False,
        "error": "RuntimeError: Unable to initialize backend 'axon': "
        "UNAVAILABLE: TPU backend setup/compile error",
    }
    session._BACKEND["suspect"] = False
    session._stage_failure_marks_backend("profile")
    assert session._BACKEND["suspect"] is True


def test_ordinary_failure_does_not_mark_backend(session):
    session.RESULTS["stages"]["pallas"] = {
        "ok": False, "error": "AssertionError: parity 0.3 > 2e-2",
    }
    session._BACKEND["suspect"] = False
    session._stage_failure_marks_backend("pallas")
    assert session._BACKEND["suspect"] is False


def test_suspect_backend_fast_fails_stage_without_running_it(
    session, monkeypatch
):
    session._BACKEND["suspect"] = True
    monkeypatch.setattr(
        session, "_backend_probe", lambda timeout=None: (False, "probe hung")
    )
    ran = []
    session._stage("bench", lambda: ran.append(1))
    rec = session.RESULTS["stages"]["bench"]
    assert ran == []
    assert rec["ok"] is False and rec.get("fast_failed") is True
    assert "probe hung" in rec["error"]
    # seconds, not a stage deadline: the whole point of the probe
    assert rec["seconds"] < 60


def test_probe_recovery_clears_suspect_and_runs_stage(session, monkeypatch):
    session._BACKEND["suspect"] = True
    monkeypatch.setattr(
        session, "_backend_probe", lambda timeout=None: (True, "probe ok")
    )
    session._stage("bench", lambda: "fine")
    rec = session.RESULTS["stages"]["bench"]
    assert rec["ok"] is True and rec["result"] == "fine"
    assert session._BACKEND["suspect"] is False


def test_backend_probe_passes_on_cpu(session):
    # scrub the axon site hook for the child: its sitecustomize overrides
    # JAX_PLATFORMS programmatically and would point the probe at the
    # tunnel (on a real session that's exactly what the probe should do;
    # this test validates the subprocess machinery on the host backend)
    from alphafold2_tpu.preflight import scrub_axon_env

    alive, why = session._backend_probe(timeout=240, env=scrub_axon_env())
    assert alive, why
