"""SidechainnetDataset crop/pad/filter logic, driven with a stubbed
``sidechainnet`` module (the package is not in this image — reference
train_pre.py:37-48 is the behavior model). The stub mimics the scn
dataloader surface the pipeline consumes: batches with ``int_seqs`` /
``msks`` / ``crds`` tensors exposing ``.numpy()``.
"""

import sys
import types

import numpy as np
import pytest

from alphafold2_tpu import constants
from alphafold2_tpu.config import DataConfig


class _Tensor:
    def __init__(self, a):
        self._a = np.asarray(a)

    def numpy(self):
        return self._a


class _Batch:
    def __init__(self, seqs, msks, crds):
        self.int_seqs = _Tensor(seqs)
        self.msks = _Tensor(msks)
        self.crds = _Tensor(crds)


def _chain_batch(lengths, pad_to):
    """One scn-style batch of prefix-masked chains, flattened atom14 coords.
    Sequences are distinct ramps so crop windows can be located later."""
    n_res = pad_to
    k = constants.NUM_COORDS_PER_RES
    seqs = np.zeros((len(lengths), n_res), np.int64)
    msks = np.zeros((len(lengths), n_res), np.int64)
    crds = np.zeros((len(lengths), n_res * k, 3), np.float32)
    for i, n in enumerate(lengths):
        seqs[i, :n] = (np.arange(n) + 7 * i) % 21
        msks[i, :n] = 1
        atoms = np.arange(n * k * 3, dtype=np.float32).reshape(n * k, 3)
        crds[i, : n * k] = atoms + 1000 * i
    return _Batch(seqs, msks, crds)


@pytest.fixture
def scn_stub(monkeypatch):
    def install(batches):
        mod = types.ModuleType("sidechainnet")
        calls = {}

        def load(casp_version, thinning, with_pytorch, batch_size,
                 dynamic_batching):
            calls.update(
                casp_version=casp_version, thinning=thinning,
                with_pytorch=with_pytorch, batch_size=batch_size,
                dynamic_batching=dynamic_batching,
            )
            return {"train": batches}

        mod.load = load
        monkeypatch.setitem(sys.modules, "sidechainnet", mod)
        return calls

    return install


def _make(cfg_kwargs, batches, scn_stub):
    from alphafold2_tpu.data.pipeline import SidechainnetDataset

    cfg = DataConfig(source="sidechainnet", **cfg_kwargs)
    calls = scn_stub(batches)
    ds = SidechainnetDataset(cfg, seed=0)
    return ds, calls


def test_scn_crop_pad_filter(scn_stub):
    # chains: 6 (below filter -> dropped), 18 (longer than crop -> cropped),
    # 10 (shorter than crop -> padded)
    L, B = 12, 2
    ds, calls = _make(
        dict(crop_len=L, msa_depth=3, msa_len=L, batch_size=B,
             min_len_filter=8, max_len_filter=200),
        [_chain_batch([6, 18, 10], pad_to=20)],
        scn_stub,
    )
    assert calls["casp_version"] == DataConfig().casp_version
    assert calls["dynamic_batching"] is False

    out = next(iter(ds))
    assert out["seq"].shape == (B, L) and out["msa"].shape == (B, 3, L)
    assert out["mask"].shape == (B, L) and out["coords"].shape == (B, L, 3)
    assert out["backbone"].shape == (B, L * 3, 3)

    # row 0 <- chain of length 18 (6 was filtered): full crop, no padding
    assert out["mask"][0].all()
    # the crop is a contiguous window of the source ramp
    d = np.diff(out["seq"][0].astype(int)) % 21
    assert np.all(d == 1)
    # coords follow the same window: CA slot of atom14, offset 1000*row_index
    k = constants.NUM_COORDS_PER_RES
    start = (
        int(out["coords"][0, 0, 0] - 1000) // (k * 3)
    )  # invert the ramp fill
    assert 0 <= start <= 18 - L
    expect_ca = (
        np.arange(18 * k * 3, dtype=np.float32).reshape(18, k, 3)[
            start : start + L, 1
        ]
        + 1000
    )
    np.testing.assert_array_equal(out["coords"][0], expect_ca)
    # backbone = N/CA/C slots of the same window
    expect_bb = (
        np.arange(18 * k * 3, dtype=np.float32).reshape(18, k, 3)[
            start : start + L, :3
        ].reshape(L * 3, 3)
        + 1000
    )
    np.testing.assert_array_equal(out["backbone"][0], expect_bb)

    # row 1 <- chain of length 10: padded tail
    assert out["mask"][1, :10].all() and not out["mask"][1, 10:].any()
    assert (out["seq"][1, 10:] == constants.AA_PAD_INDEX).all()
    np.testing.assert_array_equal(out["coords"][1, 10:], 0.0)

    # MSA synthesized from the crop: row-0 of the MSA mostly agrees with seq
    for b, w in ((0, L), (1, 10)):
        mm = out["msa_mask"][b]
        assert mm[:, :w].all() and not mm[:, w:].any()
        agree = (out["msa"][b, :, :w] == out["seq"][b, None, :w]).mean()
        assert agree > 0.6  # mutation rate ~0.15


def test_scn_skips_batches_with_no_keepable_chain(scn_stub):
    L = 8
    bad = _chain_batch([3, 2], pad_to=6)  # all below the filter
    good = _chain_batch([9], pad_to=10)
    ds, _ = _make(
        dict(crop_len=L, msa_depth=2, msa_len=L, batch_size=1,
             min_len_filter=5, max_len_filter=100),
        [bad, good],
        scn_stub,
    )
    out = next(iter(ds))
    # the first yield must come from the good batch, not crash on the bad one
    assert int(out["mask"][0].sum()) == 8


def test_scn_cycles_forever(scn_stub):
    ds, _ = _make(
        dict(crop_len=8, msa_depth=2, msa_len=8, batch_size=1,
             min_len_filter=4, max_len_filter=100),
        [_chain_batch([9], pad_to=10)],
        scn_stub,
    )
    it = iter(ds)
    outs = [next(it) for _ in range(3)]  # > one pass over the single batch
    assert all(o["seq"].shape == (1, 8) for o in outs)


def test_scn_max_len_filter_drops_long_chains(scn_stub):
    ds, _ = _make(
        dict(crop_len=8, msa_depth=2, msa_len=8, batch_size=1,
             min_len_filter=4, max_len_filter=12),
        [_chain_batch([16, 10], pad_to=20)],
        scn_stub,
    )
    out = next(iter(ds))
    # the 16-chain is filtered (>12); the 10-chain survives and is cropped
    d = np.diff(out["seq"][0].astype(int)) % 21
    assert np.all(d == 1)
    # coords carry a 1000*chain_index offset: proves the crop came from
    # chain 1, not the filtered chain 0
    assert 1000 <= out["coords"][0, 0, 0] < 2000


def test_scn_import_error_without_package(monkeypatch):
    monkeypatch.setitem(sys.modules, "sidechainnet", None)
    from alphafold2_tpu.data.pipeline import SidechainnetDataset

    with pytest.raises(ImportError, match="synthetic"):
        SidechainnetDataset(DataConfig(source="sidechainnet"))
