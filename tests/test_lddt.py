"""lDDT metric tests: perfect/degraded predictions, superposition
invariance (the property that distinguishes lDDT from RMSD), masking, and
the distogram variant against a sharp distogram oracle."""

import jax
import jax.numpy as jnp
import numpy as np

from alphafold2_tpu.utils import distogram_lddt, lddt


def _cloud(n=32, seed=0):
    return np.random.default_rng(seed).uniform(-8, 8, size=(n, 3)).astype(
        np.float32
    )


def test_perfect_prediction_scores_one():
    x = _cloud()
    assert np.isclose(float(lddt(x[None], x[None])[0]), 1.0)


def test_degrades_with_noise_and_orders_correctly():
    x = _cloud()
    rng = np.random.default_rng(1)
    scores = []
    for s in (0.1, 0.5, 2.0):
        noisy = x + rng.normal(scale=s, size=x.shape).astype(np.float32)
        scores.append(float(lddt(noisy[None], x[None])[0]))
    assert scores[0] > scores[1] > scores[2], scores
    assert scores[0] > 0.9 and scores[2] < 0.6


def test_superposition_free():
    # a rigidly moved prediction scores exactly 1.0 with NO alignment step
    x = _cloud()
    theta = 1.1
    rot = np.asarray(
        [[np.cos(theta), -np.sin(theta), 0],
         [np.sin(theta), np.cos(theta), 0], [0, 0, 1.0]], np.float32)
    moved = x @ rot.T + np.asarray([10.0, -4.0, 2.0], np.float32)
    assert np.isclose(float(lddt(moved[None], x[None])[0]), 1.0, atol=1e-5)


def test_mask_excludes_positions():
    x = _cloud()
    bad = x.copy()
    bad[-8:] += 50.0  # ruin the tail
    mask = np.ones(len(x), bool)
    full = float(lddt(bad[None], x[None], mask=mask[None])[0])
    mask[-8:] = False
    masked = float(lddt(bad[None], x[None], mask=mask[None])[0])
    assert masked > full
    assert np.isclose(masked, 1.0, atol=1e-5)  # unmasked region is perfect


def test_distogram_lddt_sharp_oracle():
    from alphafold2_tpu.utils.structure import DISTANCE_THRESHOLDS, cdist

    x = _cloud(24, seed=2)
    dist = np.asarray(cdist(x[None], x[None]))[0]
    centers = DISTANCE_THRESHOLDS - 0.25
    bins = np.abs(dist[..., None] - centers[None, None]).argmin(-1)
    sharp = jnp.asarray(
        30.0 * (np.arange(37)[None, None] == bins[..., None]), jnp.float32
    )[None]
    uniform = jnp.zeros_like(sharp)
    s_sharp = float(distogram_lddt(sharp, jnp.asarray(x)[None])[0])
    s_unif = float(distogram_lddt(uniform, jnp.asarray(x)[None])[0])
    assert s_sharp > 0.95, s_sharp
    assert s_sharp > s_unif
