"""End-to-end pipeline tests: the corrected realization of the reference's
train_end2end.py sketch (which crashes as written — SURVEY.md S2.5). Covers
the elongation reshape, the full distogram->MDS->sidechain->SE(3)->Kabsch
forward, one jitted training step, and the loss surface."""

import jax
import jax.numpy as jnp
import numpy as np

from alphafold2_tpu.config import Config, DataConfig, ModelConfig, TrainConfig
from alphafold2_tpu.data.pipeline import SyntheticDataset
from alphafold2_tpu.train.end2end import (
    End2EndModel,
    elongate,
    init_end2end_state,
    make_end2end_step,
    structure_loss,
)
from alphafold2_tpu.train.loop import device_put_batch


def tiny_cfg():
    return Config(
        model=ModelConfig(dim=32, depth=1, heads=2, dim_head=16,
                          max_seq_len=64, bfloat16=False),
        data=DataConfig(crop_len=8, msa_depth=2, msa_len=8, batch_size=1,
                        min_len_filter=8),
        train=TrainConfig(gradient_accumulate_every=1, warmup_steps=2),
    )


def tiny_model():
    return End2EndModel(dim=32, depth=1, heads=2, dim_head=16, max_seq_len=64,
                        mds_iters=20, refiner_depth=1)


def test_max_seq_len_violations_fail_loudly():
    # out-of-range positional gathers clip silently and surface as NaN
    # logits, so both the driver and the model must refuse up front
    import pytest

    from alphafold2_tpu.models import Alphafold2
    from alphafold2_tpu.train.end2end import train_end2end

    cfg = tiny_cfg()
    cfg.data.crop_len = 48  # 3*48 > max_seq_len 64
    with pytest.raises(ValueError, match="3\\*data.crop_len"):
        train_end2end(cfg, num_steps=1)

    model = Alphafold2(dim=32, depth=1, heads=2, dim_head=16, max_seq_len=16)
    seq = jnp.zeros((1, 24), jnp.int32)
    with pytest.raises(ValueError, match="max_seq_len"):
        model.init(jax.random.key(0), seq)


def test_elongate():
    seq = jnp.asarray([[3, 7]])
    mask = jnp.asarray([[True, False]])
    seq3, mask3 = elongate(seq, mask)
    assert seq3.tolist() == [[3, 3, 3, 7, 7, 7]]
    assert mask3.tolist() == [[True, True, True, False, False, False]]


def test_forward_produces_structures():
    cfg = tiny_cfg()
    batch = next(iter(SyntheticDataset(cfg.data, seed=0)))
    model = tiny_model()
    params = model.init(
        jax.random.key(0), jnp.asarray(batch["seq"]), jnp.asarray(batch["msa"]),
        mask=jnp.asarray(batch["mask"]), msa_mask=jnp.asarray(batch["msa_mask"]),
    )
    out = model.apply(
        params, jnp.asarray(batch["seq"]), jnp.asarray(batch["msa"]),
        mask=jnp.asarray(batch["mask"]), msa_mask=jnp.asarray(batch["msa_mask"]),
    )
    L = cfg.data.crop_len
    assert out["distogram"].shape == (1, 3 * L, 3 * L, 37)
    assert out["proto"].shape == (1, L, 14, 3)
    assert out["refined"].shape == (1, L, 14, 3)
    for v in out.values():
        assert np.all(np.isfinite(np.asarray(v)))
    # realized distances should be in a protein-plausible range, not collapsed
    ca = np.asarray(out["refined"])[0, :, 1]
    d = np.linalg.norm(ca[None] - ca[:, None], axis=-1)
    assert d.max() > 1.0


def test_structure_loss_zero_for_perfect_prediction():
    rng = np.random.default_rng(0)
    L = 6
    bb_true = rng.normal(scale=5.0, size=(1, 3 * L, 3)).astype(np.float32)
    refined = np.tile(
        bb_true.reshape(1, L, 3, 3)[:, :, 1:2], (1, 1, 14, 1)
    ).astype(np.float32)
    refined[:, :, :3] = bb_true.reshape(1, L, 3, 3)
    out = {
        "refined": jnp.asarray(refined),
        "weights": jnp.ones((1, 3 * L, 3 * L)),
    }
    loss, aux = structure_loss(out, jnp.asarray(bb_true), jnp.ones((1, L), bool))
    assert float(aux["rmsd"]) < 1e-3
    assert float(aux["dispersion"]) < 1e-6


def test_end2end_step_on_plm_features():
    from alphafold2_tpu.data.plm import make_provider, wrap_with_embeddings

    cfg = tiny_cfg()
    provider = make_provider("hash", dim=1280)
    stream = wrap_with_embeddings(iter(SyntheticDataset(cfg.data, seed=1)),
                                  provider)
    batch = next(stream)
    model = tiny_model()
    state = init_end2end_state(cfg, model, batch)
    step = make_end2end_step(model)
    state, metrics = step(state, device_put_batch(batch), jax.random.key(0))
    assert np.isfinite(float(metrics["loss"]))
    assert bool(metrics["grads_ok"])


def test_end2end_step_runs_and_grads_flow():
    cfg = tiny_cfg()
    batch = next(iter(SyntheticDataset(cfg.data, seed=0)))
    model = tiny_model()
    state = init_end2end_state(cfg, model, batch)
    step = make_end2end_step(model)
    state, metrics = step(state, device_put_batch(batch), jax.random.key(0))
    assert np.isfinite(float(metrics["loss"]))
    assert np.isfinite(float(metrics["rmsd"]))
    assert bool(metrics["grads_ok"])
    assert float(metrics["grad_norm"]) > 0.0  # gradients reach the trunk
