"""Pre-hardware Mosaic lowering gate (VERDICT r4 #2).

Round 4 proved the distinction between interpret-mode parity and compiled
lowering bites for real: the one on-chip Pallas attempt failed in Mosaic's
block-mapping check, an error no interpret-mode test can see. This test
runs the full Mosaic TPU lowering of every Pallas kernel entry point on the
CPU host (scripts/check_tpu_lowering.py: `.lower(lowering_platforms=
("tpu",))` in a scrubbed subprocess — the axon site hook would hang the
cross-platform trace in-process), so the NEXT tiling/layout violation is
caught in CI, not on a live chip.

The script includes its own negative control: a deliberately mis-tiled
(1, block) kernel — the exact round-4 bug class — must FAIL to lower, or
the gate reports failure. A green run therefore certifies both that the
kernels lower and that the gate can detect when they don't.
"""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCRIPT = os.path.join(REPO, "scripts", "check_tpu_lowering.py")


def test_all_pallas_kernels_lower_for_tpu():
    proc = subprocess.run(
        [sys.executable, SCRIPT],
        capture_output=True, text=True, timeout=1200,
    )
    lines = [
        json.loads(l) for l in proc.stdout.splitlines() if l.startswith("{")
    ]
    summary = next((l for l in lines if l.get("gate")), None)
    assert proc.returncode == 0, (
        f"TPU lowering gate failed (rc={proc.returncode}):\n"
        + "\n".join(
            f"  {l['case']}: {l.get('error', 'ok')}"
            for l in lines if "case" in l and not l.get("ok")
        )
        + f"\nstderr tail: {proc.stderr[-1000:]}"
    )
    assert summary is not None and summary["failed"] == []
    cases = {l["case"] for l in lines if "case" in l}
    # the negative control must have actually run — a gate that silently
    # dropped it could go green without detecting anything
    assert "negative_control_rejects_bad_tiling" in cases
    assert {
        "block_sparse_fwd_n512", "block_sparse_bwd_n1024",
        "block_sparse_custom_vjp_n512", "flash_axial_256",
    } <= cases
