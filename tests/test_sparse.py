"""Block-sparse attention tests: the dense-layout differential oracle
(sparse with all-blocks-active == dense attention — the correctness bar
SURVEY.md S7 sets for the kernel), jnp-vs-Pallas parity, layout properties,
and the module-level padding/mask behavior the reference got wrong."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from alphafold2_tpu.ops.attention import Attention
from alphafold2_tpu.ops.sparse import (
    BlockSparseConfig,
    SparseAttention,
    active_indices,
    block_sparse_attention,
)


def _qkv(key, b=2, h=2, n=64, d=16):
    ks = jax.random.split(key, 3)
    return tuple(jax.random.normal(k, (b, h, n, d)) for k in ks)


def _dense_reference(q, k, v, mask=None):
    d = q.shape[-1]
    dots = jnp.einsum("bhid,bhjd->bhij", q, k) * d**-0.5
    if mask is not None:
        dots = jnp.where(mask[:, None, None, :], dots, -1e9)
    attn = jax.nn.softmax(dots, axis=-1)
    return jnp.einsum("bhij,bhjd->bhid", attn, v)


def test_layout_properties():
    cfg = BlockSparseConfig(block_size=16, num_local_blocks=4,
                            num_global_blocks=1, num_random_blocks=2)
    lay = cfg.layout(160)
    nb = 10
    assert lay.shape == (nb, nb)
    assert lay[:1].all() and lay[:, :1].all()  # global row+col
    assert all(lay[i, i] for i in range(nb))  # local window covers diagonal
    # reference default: num_random = seq_len/block/4 (alphafold2.py:198)
    assert BlockSparseConfig(block_size=16).resolve_random(2048) == 32


def test_dense_layout_equals_dense_attention():
    q, k, v = _qkv(jax.random.key(0))
    layout = np.ones((4, 4), dtype=bool)  # 64/16 blocks, all active
    out = block_sparse_attention(q, k, v, layout, 16)
    ref = _dense_reference(q, k, v)
    assert np.allclose(out, ref, atol=1e-5), np.abs(np.asarray(out - ref)).max()


def test_dense_layout_equals_dense_attention_masked():
    q, k, v = _qkv(jax.random.key(1))
    mask = jnp.ones((2, 64), dtype=bool).at[:, 50:].set(False)
    layout = np.ones((4, 4), dtype=bool)
    out = block_sparse_attention(q, k, v, layout, 16, mask=mask)
    ref = _dense_reference(q, k, v, mask=mask)
    assert np.allclose(out[:, :, :50], ref[:, :, :50], atol=1e-5)


def test_sparse_layout_restricts_attention():
    # only the diagonal block active -> each block attends only to itself
    q, k, v = _qkv(jax.random.key(2), n=32)
    layout = np.eye(2, dtype=bool)
    out = block_sparse_attention(q, k, v, layout, 16)
    ref0 = _dense_reference(q[:, :, :16], k[:, :, :16], v[:, :, :16])
    assert np.allclose(out[:, :, :16], ref0, atol=1e-5)


def test_pallas_matches_jnp():
    q, k, v = _qkv(jax.random.key(3), n=64, d=16)
    cfg = BlockSparseConfig(block_size=16, num_random_blocks=1)
    layout = cfg.layout(64)
    mask = jnp.ones((2, 64), dtype=bool).at[:, 60:].set(False)
    from alphafold2_tpu.ops.pallas.block_sparse import pallas_block_sparse_attention

    ref = block_sparse_attention(q, k, v, layout, 16, mask=mask)
    out = pallas_block_sparse_attention(q, k, v, layout, 16, mask=mask,
                                        interpret=True)
    assert np.allclose(out, ref, atol=1e-4), np.abs(np.asarray(out - ref)).max()


def test_pallas_dense_layout_oracle():
    q, k, v = _qkv(jax.random.key(4), n=32, d=8)
    layout = np.ones((2, 2), dtype=bool)
    from alphafold2_tpu.ops.pallas.block_sparse import pallas_block_sparse_attention

    out = pallas_block_sparse_attention(q, k, v, layout, 16, interpret=True)
    ref = _dense_reference(q, k, v)
    assert np.allclose(out, ref, atol=1e-4)


def test_pallas_path_is_differentiable_and_grads_match_jnp():
    # training goes through value_and_grad: the Pallas forward must carry a
    # VJP (raw pallas_call kernels have none) and its gradients must equal
    # the jnp oracle's
    kw = dict(dim=32, heads=2, dim_head=16, seq_len=64,
              config=BlockSparseConfig(block_size=16, num_random_blocks=1))
    x = jax.random.normal(jax.random.key(10), (1, 32, 32))
    mask = jnp.ones((1, 32), dtype=bool).at[:, 28:].set(False)
    m_jnp = SparseAttention(use_pallas=False, **kw)
    m_pal = SparseAttention(use_pallas=True, **kw)  # interpret mode on CPU
    params = m_jnp.init(jax.random.key(11), x, mask=mask)

    def loss(model, p):
        return jnp.sum(model.apply(p, x, mask=mask) ** 2)

    l1, g1 = jax.value_and_grad(lambda p: loss(m_jnp, p))(params)
    l2, g2 = jax.value_and_grad(lambda p: loss(m_pal, p))(params)
    assert np.isclose(float(l1), float(l2), rtol=1e-5)
    for a, b in zip(jax.tree.leaves(g1), jax.tree.leaves(g2)):
        assert np.allclose(a, b, atol=1e-4), np.abs(np.asarray(a - b)).max()


def test_sparse_module_pads_and_preserves_mask():
    # n=40 not a block multiple: module pads to 48 and composes the caller
    # mask instead of overwriting it (the reference clobbers it,
    # alphafold2.py:222 — SURVEY.md S2.5)
    module = SparseAttention(
        dim=32, heads=2, dim_head=16, seq_len=64,
        config=BlockSparseConfig(block_size=16, num_random_blocks=0),
    )
    x = jax.random.normal(jax.random.key(5), (1, 40, 32))
    mask = jnp.ones((1, 40), dtype=bool).at[:, 30:].set(False)
    params = module.init(jax.random.key(6), x, mask=mask)
    out = module.apply(params, x, mask=mask)
    assert out.shape == (1, 40, 32)
    # masked-out keys must not influence unmasked outputs: perturb them
    x2 = x.at[:, 35:].add(100.0)
    out2 = module.apply(params, x2, mask=mask)
    assert np.allclose(out[:, :30], out2[:, :30], atol=1e-5)


def test_model_sparse_pallas_path_matches_jnp():
    # the Pallas kernel must be reachable from the model config and agree
    # with the gather-based jnp path on identical params
    from alphafold2_tpu.models import Alphafold2

    kw = dict(
        dim=32, depth=1, heads=2, dim_head=16, max_seq_len=512,
        sparse_self_attn=True,
        sparse_config=BlockSparseConfig(block_size=16, num_random_blocks=0),
    )
    seq = jax.random.randint(jax.random.key(20), (1, 16), 0, 21)
    mask = jnp.ones((1, 16), dtype=bool)
    m_jnp = Alphafold2(sparse_use_pallas=False, **kw)
    m_pal = Alphafold2(sparse_use_pallas=True, **kw)  # interpret mode on CPU
    params = m_jnp.init(jax.random.key(21), seq, mask=mask)
    out_jnp = m_jnp.apply(params, seq, mask=mask)
    out_pal = m_pal.apply(params, seq, mask=mask)
    assert np.allclose(out_jnp, out_pal, atol=2e-3), (
        np.abs(np.asarray(out_jnp - out_pal)).max()
    )


def test_model_with_sparse_attn():
    from alphafold2_tpu.models import Alphafold2

    model = Alphafold2(
        dim=32, depth=2, heads=2, dim_head=16, max_seq_len=512,
        sparse_self_attn=(True, False),
    )
    seq = jax.random.randint(jax.random.key(7), (1, 8), 0, 21)
    msa = jax.random.randint(jax.random.key(8), (1, 2, 8), 0, 21)
    mask = jnp.ones((1, 8), dtype=bool)
    msa_mask = jnp.ones((1, 2, 8), dtype=bool)
    params = model.init(jax.random.key(9), seq, msa, mask=mask, msa_mask=msa_mask)
    out = model.apply(params, seq, msa, mask=mask, msa_mask=msa_mask)
    assert out.shape == (1, 8, 8, 37)
    assert np.all(np.isfinite(out))


def test_pallas_fused_backward_matches_oracle_primitive():
    """dq/dk/dv from the fused Pallas backward kernels == jax.vjp through
    the gather-based jnp oracle, on a random sparse layout with masking."""
    from alphafold2_tpu.ops.sparse import (
        BlockSparseConfig, block_sparse_attention,
        block_sparse_attention_pallas,
    )

    b, h, n, d, bs = 2, 2, 64, 16, 16
    layout = BlockSparseConfig(block_size=bs, num_random_blocks=1, seed=3).layout(n)
    ks = jax.random.split(jax.random.key(20), 4)
    q, k, v = (jax.random.normal(kk, (b, h, n, d)) for kk in ks[:3])
    g = jax.random.normal(ks[3], (b, h, n, d))
    mask = jnp.ones((b, n), bool).at[:, 57:].set(False)

    def run(fn):
        out, vjp = jax.vjp(lambda q, k, v: fn(q, k, v), q, k, v)
        return out, vjp(g)

    out_o, (dq_o, dk_o, dv_o) = run(
        lambda q, k, v: block_sparse_attention(q, k, v, layout, bs, mask=mask)
    )
    out_p, (dq_p, dk_p, dv_p) = run(
        lambda q, k, v: block_sparse_attention_pallas(q, k, v, layout, bs,
                                                      mask=mask)
    )
    np.testing.assert_allclose(np.asarray(out_p), np.asarray(out_o), atol=1e-5)
    np.testing.assert_allclose(np.asarray(dq_p), np.asarray(dq_o), atol=1e-4)
    np.testing.assert_allclose(np.asarray(dk_p), np.asarray(dk_o), atol=1e-4)
    np.testing.assert_allclose(np.asarray(dv_p), np.asarray(dv_o), atol=1e-4)


def test_splash_backend_matches_jnp_valid_region():
    """config.backend="splash" (the stock jax splash-attention kernel over
    the same layout, interpret mode on CPU): values AND grads match the
    gather-based jnp oracle on the valid region. Padded query rows are
    unspecified (downstream masking excludes them from the loss, so their
    grads are zero either way)."""
    from alphafold2_tpu.ops.sparse import (
        BlockSparseConfig, block_sparse_attention,
        block_sparse_attention_splash,
    )

    b, h, n, d, bs = 2, 2, 512, 64, 128
    cfg = BlockSparseConfig(block_size=bs, num_local_blocks=2,
                            num_global_blocks=1, num_random_blocks=1, seed=5)
    layout = cfg.layout(n)
    ks = jax.random.split(jax.random.key(30), 3)
    q, k, v = (jax.random.normal(kk, (b, h, n, d)) for kk in ks)
    mask = jnp.ones((b, n), bool).at[:, -17:].set(False)
    valid = np.asarray(mask)[:, None, :, None]

    ref = block_sparse_attention(q, k, v, layout, bs, mask=mask)
    try:
        out = block_sparse_attention_splash(q, k, v, layout, bs, mask=mask)
    except NotImplementedError as e:
        if "head_dim" in str(e):
            pytest.skip(
                "environment gate: this jax build's splash-attention "
                f"kernel rejects the config ({e})"
            )
        raise
    np.testing.assert_allclose(
        np.asarray(out) * valid, np.asarray(ref) * valid, atol=2e-5
    )

    def loss(fn):
        # masked sum: only valid-region outputs contribute, like a real loss
        return lambda q: jnp.sum((fn(q) * valid) ** 2)

    g_ref = jax.grad(loss(
        lambda q: block_sparse_attention(q, k, v, layout, bs, mask=mask)
    ))(q)
    g_spl = jax.grad(loss(
        lambda q: block_sparse_attention_splash(q, k, v, layout, bs, mask=mask)
    ))(q)
    np.testing.assert_allclose(
        np.asarray(g_spl), np.asarray(g_ref), atol=2e-4
    )


def test_splash_backend_selected_by_config(monkeypatch):
    # config.backend routes the module; explicit use_pallas keeps winning
    from alphafold2_tpu.ops import sparse as sparse_mod
    from alphafold2_tpu.ops.sparse import BlockSparseConfig, SparseAttention

    called = {}

    def fake_splash(q, k, v, layout, bs, mask=None):
        called["splash"] = True
        return jnp.zeros_like(q)

    monkeypatch.setattr(sparse_mod, "block_sparse_attention_splash",
                        fake_splash)
    x = jax.random.normal(jax.random.key(31), (1, 64, 32))
    m = SparseAttention(
        dim=32, heads=2, dim_head=16,
        config=BlockSparseConfig(block_size=16, backend="splash"),
    )
    params = m.init(jax.random.key(32), x)
    m.apply(params, x)
    assert called.get("splash")

    called.clear()
    m2 = SparseAttention(
        dim=32, heads=2, dim_head=16, use_pallas=False,
        config=BlockSparseConfig(block_size=16, backend="splash"),
    )
    params2 = m2.init(jax.random.key(33), x)
    m2.apply(params2, x)
    assert not called  # explicit use_pallas=False -> jnp oracle, not splash


def test_splash_backend_unaligned_falls_back():
    # seq lengths not divisible by the splash kernel's 128 block fall back
    # to the jnp oracle (warn-once, never crash) — same contract as flash
    from alphafold2_tpu.ops.sparse import (
        BlockSparseConfig, block_sparse_attention,
        block_sparse_attention_splash,
    )

    b, h, n, d, bs = 1, 2, 64, 16, 16
    layout = BlockSparseConfig(block_size=bs, num_random_blocks=0).layout(n)
    ks = jax.random.split(jax.random.key(40), 3)
    q, k, v = (jax.random.normal(kk, (b, h, n, d)) for kk in ks)
    out = block_sparse_attention_splash(q, k, v, layout, bs)
    ref = block_sparse_attention(q, k, v, layout, bs)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-6)


def test_block_layout_mask_indexing_matches_dense():
    """_BlockLayoutMask.__getitem__ must honor numpy's dense-ndarray
    indexing semantics for every index form splash (or a future jax) might
    use: slice+slice and slice+array are outer-product, array+array is
    element-wise paired/broadcast (ADVICE r3: np.ix_ on a resolved integer
    pair silently returned an outer-product block of the wrong shape)."""
    from alphafold2_tpu.ops.sparse import (
        BlockSparseConfig, _block_layout_mask_cls,
    )

    pytest.importorskip(
        "jax.experimental.pallas.ops.tpu.splash_attention"
    )
    bs, n = 16, 128
    layout = BlockSparseConfig(block_size=bs, num_random_blocks=2,
                               seed=3).layout(n)
    dense = np.kron(layout, np.ones((bs, bs), dtype=bool))
    mask = _block_layout_mask_cls()(layout, bs)
    assert mask.shape == dense.shape

    cases = [
        (slice(0, 48), slice(32, 128)),            # slice+slice chunk
        (slice(None), slice(None)),                # full
        (np.array([0, 17, 40, 99]), np.array([5, 33, 64, 127])),  # paired
        (np.array([[0], [31]]), np.array([2, 70])),  # broadcast pair
        (slice(16, 80), np.array([0, 50, 90])),    # slice+array outer
        (np.array([3, 77]), slice(0, 64)),         # array+slice outer
        (7, np.array([0, 64, 100])),               # int+array broadcast
        (slice(0, 32), 65),                        # slice+int
    ]
    for idx in cases:
        expect = dense[idx]
        got = mask[idx]
        assert np.asarray(got).shape == np.asarray(expect).shape, idx
        np.testing.assert_array_equal(np.asarray(got), expect, err_msg=str(idx))
