"""Gradient-parity test for the remat trunk — the analogue of reference
tests/test_reversible.py: the memory-saving path must produce the same
gradients as the plain path (there: custom reversible backward vs autograd;
here: jax.checkpoint rematerialization vs no remat)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from alphafold2_tpu.models import Alphafold2
from alphafold2_tpu.models.trunk import Trunk


@pytest.mark.slow
def test_remat_trunk_grad_parity():
    dim, n, m = 16, 6, 2
    key = jax.random.key(0)
    x = jax.random.normal(jax.random.fold_in(key, 1), (1, n, n, dim))
    msa = jax.random.normal(jax.random.fold_in(key, 2), (1, m, n, dim))

    def build(remat):
        return Trunk(dim=dim, depth=2, heads=2, dim_head=8, remat=remat)

    params = build(False).init(jax.random.key(3), x, msa)

    def loss(trunk, params, x, msa):
        xo, mo = trunk.apply(params, x, msa)
        return jnp.sum(xo**2) + jnp.sum(mo**2)

    g_plain = jax.grad(loss, argnums=(2, 3))(build(False), params, x, msa)
    g_remat = jax.grad(loss, argnums=(2, 3))(build(True), params, x, msa)
    # same parameters, same math: gradients must match to float tolerance
    for a, b in zip(g_plain, g_remat):
        assert np.allclose(a, b, atol=1e-3), np.abs(np.asarray(a - b)).max()


def test_remat_model_backward_runs():
    # reference tests/test_attention.py:75-97 (reversible variant + backward)
    model = Alphafold2(
        dim=32, depth=2, heads=2, dim_head=16, max_seq_len=64, remat=True
    )
    seq = jax.random.randint(jax.random.key(0), (1, 12), 0, 21)
    msa = jax.random.randint(jax.random.key(1), (1, 3, 12), 0, 21)
    mask = jnp.ones((1, 12), dtype=bool)
    msa_mask = jnp.ones((1, 3, 12), dtype=bool)
    params = model.init(jax.random.key(2), seq, msa, mask=mask, msa_mask=msa_mask)

    def loss(p):
        return jnp.sum(model.apply(p, seq, msa, mask=mask, msa_mask=msa_mask))

    g = jax.jit(jax.grad(loss))(params)
    assert all(np.all(np.isfinite(l)) for l in jax.tree.leaves(g))


def test_remat_param_isomorphic():
    # remat and plain configs must have identical parameter trees (the
    # reference's two engines are NOT isomorphic — SURVEY.md S2.5)
    dim, n, m = 16, 4, 2
    x = jnp.zeros((1, n, n, dim))
    msa = jnp.zeros((1, m, n, dim))
    p1 = Trunk(dim=dim, depth=2, heads=2, dim_head=8, remat=False).init(
        jax.random.key(0), x, msa
    )
    p2 = Trunk(dim=dim, depth=2, heads=2, dim_head=8, remat=True).init(
        jax.random.key(0), x, msa
    )
    s1 = jax.tree.structure(p1)
    s2 = jax.tree.structure(p2)
    assert s1 == s2
    for a, b in zip(jax.tree.leaves(p1), jax.tree.leaves(p2)):
        assert a.shape == b.shape
        assert np.allclose(a, b)


@pytest.mark.slow
def test_remat_policy_grad_parity():
    """remat_policy="dots"/"dots_no_batch" (save matmul outputs, skip their
    recompute in backward) must not change gradients — only the
    memory/recompute schedule. Unknown policies fail loudly."""
    import pytest

    dim, n, m = 16, 6, 2
    key = jax.random.key(10)
    x = jax.random.normal(jax.random.fold_in(key, 1), (1, n, n, dim))
    msa = jax.random.normal(jax.random.fold_in(key, 2), (1, m, n, dim))

    def build(remat, policy=None, scan=False):
        return Trunk(dim=dim, depth=2, heads=2, dim_head=8, remat=remat,
                     remat_policy=policy, scan_layers=scan)

    params = build(False).init(jax.random.key(3), x, msa)

    def loss(trunk, params, x, msa):
        xo, mo = trunk.apply(params, x, msa)
        return jnp.sum(xo**2) + jnp.sum(mo**2)

    g_plain = jax.grad(loss, argnums=(2, 3))(build(False), params, x, msa)
    for policy in ("dots", "dots_no_batch", "nothing"):
        g_pol = jax.grad(loss, argnums=(2, 3))(
            build(True, policy), params, x, msa
        )
        for a, b in zip(g_plain, g_pol):
            assert np.allclose(a, b, atol=1e-3), (
                policy, np.abs(np.asarray(a - b)).max()
            )

    # scan_layers route applies the policy inside the scan body
    scan_params = build(False, scan=True).init(jax.random.key(3), x, msa)
    g_scan = jax.grad(loss, argnums=(2, 3))(
        build(False, scan=True), scan_params, x, msa
    )
    g_scan_pol = jax.grad(loss, argnums=(2, 3))(
        build(True, "dots", scan=True), scan_params, x, msa
    )
    for a, b in zip(g_scan, g_scan_pol):
        assert np.allclose(a, b, atol=1e-3)

    with pytest.raises(ValueError, match="unknown remat_policy"):
        jax.grad(loss, argnums=(2,))(build(True, "bogus"), params, x, msa)

    # a real policy without remat (or with the reversible engine) is a
    # silent no-op the trunk must reject; "nothing" is the explicit default
    # spelling and stays allowed
    with pytest.raises(ValueError, match="has no effect"):
        build(False, "dots").apply(params, x, msa)
    with pytest.raises(ValueError, match="reversible"):
        Trunk(dim=dim, depth=2, heads=2, dim_head=8, reversible=True,
              remat=True, remat_policy="dots").init(jax.random.key(4), x, msa)
    build(False, "nothing").apply(params, x, msa)  # alias of None: fine
