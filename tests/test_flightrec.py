"""Flight recorder tests (observe/flightrec.py): environment scrubbing,
bounded rings, once-per-reason dump semantics — plus the ISSUE 9
acceptance subprocess test: a simulated LivenessWatchdog fire (the same
AF2TPU_BENCH_SIMULATE_HANG rig tests/test_bench_liveness.py uses) must
leave a scrubbed incident dump on disk beside the structured failure
record."""

import glob
import json
import os
import signal
import subprocess
import sys
import time

import pytest

from alphafold2_tpu.observe.flightrec import (
    REDACTED,
    FlightRecorder,
    install,
    install_signal_handler,
    maybe_install_from_env,
    scrub_env,
)
from alphafold2_tpu.observe.tracing import Tracer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _isolate_singleton():
    from alphafold2_tpu.observe import flightrec

    saved = flightrec._ACTIVE["recorder"]
    flightrec._ACTIVE["recorder"] = None
    yield
    flightrec._ACTIVE["recorder"] = saved


# ------------------------------------------------------------------ scrub


def test_scrub_env_redacts_secrets_and_drops_axon():
    env = {
        "MY_API_KEY": "hunter2",
        "SOME_TOKEN": "abc",
        "DB_PASSWORD": "pw",
        "AUTH_HEADER": "Bearer x",
        "AXON_ENDPOINT": "http://internal",
        "PALLAS_AXON_MODE": "remote",
        "JAX_PLATFORMS": "cpu",
        "PATH": "/usr/bin",
    }
    out = scrub_env(env)
    assert out["MY_API_KEY"] == REDACTED
    assert out["SOME_TOKEN"] == REDACTED
    assert out["DB_PASSWORD"] == REDACTED
    assert out["AUTH_HEADER"] == REDACTED
    assert "AXON_ENDPOINT" not in out
    assert "PALLAS_AXON_MODE" not in out
    assert out["JAX_PLATFORMS"] == "cpu"  # non-secrets pass through
    assert out["PATH"] == "/usr/bin"
    assert list(out) == sorted(out)  # deterministic ordering


# ------------------------------------------------------------------- rings


def test_dump_contains_rings_and_is_once_per_reason(tmp_path):
    rec = FlightRecorder(directory=str(tmp_path), capacity=32)
    tracer = Tracer(enabled=True)
    rec.attach(tracer)
    for i in range(50):  # more than capacity: ring keeps the newest
        tracer.instant(f"ev{i}")
    rec.note("dispatch_error", bucket=16, error="boom")
    rec.snapshot("registry", {"sched.admitted": 3})

    path = rec.dump("test_reason", extra={"detail": 7})
    assert path and os.path.exists(path)
    doc = json.load(open(path))
    assert doc["reason"] == "test_reason" and doc["extra"]["detail"] == 7
    assert doc["pid"] == os.getpid()
    names = [e["name"] for e in doc["events"]]
    assert len(names) == 32 and names[-1] == "ev49"  # bounded, newest kept
    assert doc["notes"][0]["kind"] == "dispatch_error"
    assert doc["metric_snapshots"][0]["data"] == {"sched.admitted": 3}

    # second dump for the same reason is suppressed; force overrides
    assert rec.dump("test_reason") is None
    assert rec.dump("test_reason", force=True) is not None
    assert rec.dump("other_reason") is not None


def test_dump_without_directory_is_a_noop():
    rec = FlightRecorder(directory=None)
    if not os.environ.get("AF2TPU_FLIGHTREC_DIR"):
        assert rec.dump("x") is None


def test_maybe_install_from_env(tmp_path, monkeypatch):
    monkeypatch.delenv("AF2TPU_FLIGHTREC_DIR", raising=False)
    assert maybe_install_from_env() is None
    monkeypatch.setenv("AF2TPU_FLIGHTREC_DIR", str(tmp_path))
    rec = maybe_install_from_env()
    assert rec is not None and rec.directory == str(tmp_path)
    assert maybe_install_from_env() is rec  # idempotent


# ----------------------------------------------------------------- signals


def test_sigterm_dump_in_subprocess(tmp_path):
    """The installed handler dumps on SIGTERM and the process still dies
    BY the signal (default semantics restored and re-raised)."""
    code = (
        "import os, signal, time\n"
        "from alphafold2_tpu.observe.flightrec import ("
        "FlightRecorder, install_signal_handler)\n"
        f"rec = FlightRecorder(directory={str(tmp_path)!r})\n"
        "rec.note('alive')\n"
        "install_signal_handler(rec)\n"
        "os.kill(os.getpid(), signal.SIGTERM)\n"
        "time.sleep(5)\n"  # never reached: the re-raise kills us
    )
    r = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True, text=True, timeout=60,
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
    )
    assert r.returncode == -signal.SIGTERM, (r.returncode, r.stderr[-500:])
    dumps = glob.glob(str(tmp_path / "incident_sigterm_*.json"))
    assert len(dumps) == 1
    doc = json.load(open(dumps[0]))
    assert doc["notes"][0]["kind"] == "alive"
    assert doc["notes"][-1]["kind"] == "signal"


def test_install_signal_handler_off_main_thread_is_noop():
    import threading

    rec = FlightRecorder(directory=None)
    done = []
    t = threading.Thread(
        target=lambda: (install_signal_handler(rec), done.append(1))
    )
    t.start()
    t.join()
    assert done == [1]  # swallowed the ValueError, did not crash


# ----------------------------------------- watchdog-fire acceptance (slow)


@pytest.mark.slow
def test_liveness_watchdog_fire_dumps_incident(tmp_path):
    """ISSUE 9 acceptance: a simulated watchdog fire (hung backend_init +
    hung probe) produces BOTH the structured liveness-dead record on
    stdout AND a scrubbed incident dump whose env carries no AXON_ keys
    and no secret values."""
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        AF2TPU_PLATFORM="cpu",
        AF2TPU_BENCH_MODE="serve",
        AF2TPU_SERVE_BUCKETS="8",
        AF2TPU_SERVE_REQUESTS="2",
        AF2TPU_BENCH_SIMULATE_HANG="backend_init:300",
        AF2TPU_BENCH_INIT_DEADLINE="2",
        AF2TPU_LIVENESS_TIMEOUT="3",
        AF2TPU_LIVENESS_PROBE_CODE="import time; time.sleep(120)",
        AF2TPU_FLIGHTREC_DIR=str(tmp_path),
        # planted contraband the dump must not leak
        FAKE_SERVICE_TOKEN="tip-top-secret",
        AXON_PLANTED="internal-endpoint",
    )
    t0 = time.monotonic()
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        capture_output=True, text=True, timeout=55, env=env,
    )
    assert time.monotonic() - t0 < 55

    (line,) = [ln for ln in r.stdout.splitlines() if ln.strip()]
    record = json.loads(line)
    assert record["liveness"] == "dead"

    dumps = glob.glob(str(tmp_path / "incident_liveness_dead_*.json"))
    assert len(dumps) == 1, (os.listdir(tmp_path), r.stderr[-800:])
    doc = json.load(open(dumps[0]))
    assert doc["reason"] == "liveness_dead"
    assert doc["extra"]["stage"] == "serve:backend_init"
    assert doc["env"]["FAKE_SERVICE_TOKEN"] == REDACTED
    assert not any(k.startswith("AXON_") for k in doc["env"])
