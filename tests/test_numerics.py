"""Numerics telemetry tests: tagged stats under jit match an unjitted
reference, disabled tags add zero ops, NaN triage names the poisoned trunk
block, and the train loop emits the triage report + first_step_s /
per-group-norm / flops metrics end to end."""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np

from alphafold2_tpu.config import Config, DataConfig, ModelConfig, TrainConfig
from alphafold2_tpu.observe import numerics


def tiny_config(depth=1, **train_kw):
    return Config(
        model=ModelConfig(dim=32, depth=depth, heads=2, dim_head=16,
                          max_seq_len=64, bfloat16=False),
        data=DataConfig(crop_len=16, msa_depth=2, msa_len=16, batch_size=1,
                        min_len_filter=8),
        train=TrainConfig(gradient_accumulate_every=1, warmup_steps=1,
                          **train_kw),
    )


# ------------------------------------------------------------ tag mechanics


def test_tag_without_collection_is_identity_and_free():
    x = jnp.arange(6.0).reshape(2, 3)
    assert numerics.tag("t", x) is x
    # zero overhead when disabled: the jaxpr is IDENTICAL to untagged code
    tagged = jax.make_jaxpr(lambda a: numerics.tag("a", a) * 2.0)(x)
    plain = jax.make_jaxpr(lambda a: a * 2.0)(x)
    assert str(tagged) == str(plain)


def test_stats_match_unjitted_reference():
    arr = np.array([[1.0, -2.0, np.nan], [np.inf, 3.0, 0.5]], np.float32)

    def f(a):
        with numerics.collect() as col:
            numerics.tag("x", a)
            return col.stats()

    finite = arr[np.isfinite(arr)]
    for fn in (f, jax.jit(f)):  # eager and jitted agree with numpy
        s = jax.device_get(fn(jnp.asarray(arr)))["x"]
        np.testing.assert_allclose(s["l2"], np.linalg.norm(finite), rtol=1e-6)
        assert s["max_abs"] == 3.0
        assert s["nan_count"] == 1 and s["inf_count"] == 1


def test_tag_order_survives_jit_and_dedupes():
    def f(a):
        with numerics.collect() as col:
            numerics.tag("zz", a)
            numerics.tag("aa", a + 1)
            numerics.tag("zz", a * jnp.nan)
            return col.stats()

    stats = jax.device_get(jax.jit(f)(jnp.ones(3)))
    # jit sorts dict keys in its output pytree; the recorded index is what
    # restores topological (tag) order
    assert [n for n, _ in numerics._ordered(stats)] == ["zz", "aa", "zz#2"]
    assert numerics.first_nonfinite(stats) == "zz#2"


def test_flatten_and_report_helpers():
    with numerics.collect() as col:
        numerics.tag("good", jnp.ones(4))
        numerics.tag("bad", jnp.array([1.0, jnp.inf]))
    stats = col.stats()
    flat = numerics.flatten_stats(stats)
    assert flat["numerics/bad/inf_count"] == 1.0
    assert not any(k.endswith("/index") for k in flat)
    report = numerics.triage_report(stats, step=3)
    assert report["event"] == "nan_triage"
    assert report["step"] == 3
    assert report["first_nonfinite"] == "bad"
    assert report["nonfinite"] == ["bad"]
    assert report["tensors"]["good"]["nan_count"] == 0


def test_collect_disabled_and_tree_stats():
    with numerics.collect(enabled=False) as col:
        numerics.tag("x", jnp.ones(3))
    assert col.stats() == {}
    s = numerics.tree_stats({"a": jnp.ones(4), "b": jnp.full(2, jnp.nan)})
    assert float(s["l2"]) == 2.0 and float(s["nan_count"]) == 2


# ------------------------------------------------------- train-step wiring


def _batch_and_model(cfg):
    from alphafold2_tpu.data.pipeline import SyntheticDataset
    from alphafold2_tpu.train.loop import build_model, init_state

    batch = next(iter(SyntheticDataset(cfg.data, seed=0)))
    model = build_model(cfg)
    return batch, model, init_state(cfg, model, batch)


def _poison(params, key_name):
    """NaN every leaf under the named module subtree."""
    import jax.tree_util as jtu

    flat, _ = jtu.tree_flatten_with_path(params)
    leaves = [
        np.full_like(v, np.nan)
        if any(getattr(k, "key", None) == key_name for k in path) else v
        for path, v in flat
    ]
    return jax.tree.unflatten(jax.tree.structure(params), leaves)


def test_full_mode_step_carries_numerics_and_group_norms():
    from alphafold2_tpu.train.loop import device_put_batch, make_train_step

    cfg = tiny_config()
    batch, model, state = _batch_and_model(cfg)
    step = make_train_step(model, numerics_mode="full")
    _, metrics = step(state, device_put_batch(batch), jax.random.key(0))
    stats = metrics["numerics"]
    assert {"embed.pair", "trunk.layer_0.pair", "distogram.logits",
            "loss.distogram_nll"} <= set(stats)
    assert numerics.first_nonfinite(stats) is None
    assert any(k.startswith("grad_norm/") for k in metrics)
    assert any(k.startswith("param_norm/") for k in metrics)
    assert any(k.startswith("update_norm/") for k in metrics)


def test_triage_names_poisoned_trunk_layer():
    """The ISSUE's acceptance demo: poison one trunk block's weights; the
    triage report names that block as the first non-finite tensor."""
    from alphafold2_tpu.train.loop import device_put_batch, make_triage_step

    cfg = tiny_config(depth=2)
    batch, model, state = _batch_and_model(cfg)
    poisoned = _poison(state.params, "layer_1")
    triage = make_triage_step(model)
    stats = triage(poisoned, device_put_batch(batch), jax.random.key(1))
    report = numerics.triage_report(stats)
    assert report["first_nonfinite"] == "trunk.layer_1.pair"
    assert float(stats["trunk.layer_0.pair"]["nan_count"]) == 0
    assert "grad/trunk" in stats  # per-group gradient stats follow the loss
    # clean params through the same compiled triage: everything finite
    clean = triage(state.params, device_put_batch(batch), jax.random.key(1))
    assert numerics.first_nonfinite(clean) is None


def test_train_loop_triage_and_first_step_metrics(tmp_path):
    """End to end: a poisoned restored checkpoint makes every step skip; the
    loop AOT-compiles (compile_s + step_flops metrics), logs first_step_s
    instead of the old steps_per_sec=0.0 placeholder, records per-group
    norms, and emits a nan_triage report naming the poisoned block."""
    from alphafold2_tpu.train.checkpoint import CheckpointManager
    from alphafold2_tpu.train.loop import train

    cfg = tiny_config(num_steps=3, log_every=1,
                      checkpoint_dir=str(tmp_path), checkpoint_every=1000)
    _, _, state = _batch_and_model(cfg)
    state = state.replace(params=_poison(state.params, "pair_ff"))
    mgr = CheckpointManager(str(tmp_path), keep=2)
    mgr.save(1, state)
    mgr.wait()
    mgr.close()

    final = train(cfg)  # restores at step 1, runs steps 1 and 2
    assert int(final.skipped) == 2

    with open(os.path.join(str(tmp_path), "metrics.jsonl")) as f:
        records = [json.loads(line) for line in f if line.strip()]
    assert any("compile_s" in r and "step_flops" in r for r in records)
    assert any("first_step_s" in r for r in records)
    assert not any(r.get("steps_per_sec") == 0.0 for r in records)
    step_recs = [r for r in records if "loss" in r]
    assert any("grad_norm/trunk" in r for r in step_recs)
    triages = [r for r in records if r.get("event") == "nan_triage"]
    assert triages, records
    assert triages[0]["first_nonfinite"].startswith("trunk.layer_0")
    assert triages[0]["numerics/trunk.layer_0.pair/nan_count"] > 0
