"""Structure-math oracle tests.

Coverage model: reference tests/test_utils.py, upgraded from shape-smoke to
value assertions wherever a numeric oracle exists.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from alphafold2_tpu import constants
from alphafold2_tpu.utils import (
    center_distogram,
    get_bucketed_distance_matrix,
    get_dihedral,
    nerf,
    scn_backbone_mask,
    scn_cloud_mask,
    sidechain_container,
)


def test_bucketed_distance_matrix_values():
    # three points on a line at 0, 3, 25 Angstroms
    coords = jnp.array([[[0.0, 0, 0], [3.0, 0, 0], [25.0, 0, 0]]])
    mask = jnp.array([[True, True, False]])
    buckets = get_bucketed_distance_matrix(coords, mask)
    # bin width = 18/36 = 0.5; d=3 -> index of first boundary >= 3 is (3-2)/0.5 = 2
    assert buckets.shape == (1, 3, 3)
    assert buckets[0, 0, 0] == 0  # self-distance 0 < 2 -> bucket 0
    assert buckets[0, 0, 1] == 2
    assert buckets[0, 0, 2] == -100  # masked
    assert buckets[0, 2, 2] == -100


def test_bucketed_distance_clamps_far():
    coords = jnp.array([[[0.0, 0, 0], [100.0, 0, 0]]])
    mask = jnp.ones((1, 2), dtype=bool)
    buckets = get_bucketed_distance_matrix(coords, mask)
    assert buckets[0, 0, 1] == constants.DISTOGRAM_BUCKETS - 1


def test_center_distogram_mean_and_median():
    key = jax.random.key(0)
    logits = jax.random.normal(key, (1, 16, 16, 37))
    probs = jax.nn.softmax(logits, axis=-1)
    for mode in ("mean", "median"):
        central, weights = center_distogram(probs, center=mode)
        assert central.shape == (1, 16, 16)
        assert weights.shape == (1, 16, 16)
        # diagonal zeroed
        assert np.allclose(np.diagonal(central[0]), 0.0)
        assert np.all(np.isfinite(central)) and np.all(np.isfinite(weights))
        assert np.all(weights >= 0) and np.all(weights <= 1)


def test_center_distogram_peaked_distogram_recovers_distance():
    # a distogram sharply peaked at bucket k should produce that bin's center
    n = 4
    probs = np.zeros((1, n, n, 37), dtype=np.float32)
    probs[..., 10] = 1.0
    central, weights = center_distogram(jnp.asarray(probs), center="mean")
    bins = np.linspace(2, 20, 37)
    expected = bins[10] - 0.5 * (bins[2] - bins[1])
    off_diag = central[0][~np.eye(n, dtype=bool)]
    assert np.allclose(off_diag, expected, atol=1e-5)
    # zero dispersion -> weight 1 off-diagonal
    w_off = weights[0][~np.eye(n, dtype=bool)]
    assert np.allclose(w_off, 1.0, atol=1e-5)


def test_backbone_masks():
    seqs = jnp.zeros((2, 50), dtype=jnp.int32)
    n_mask, ca_mask = scn_backbone_mask(seqs, boolean=True, l_aa=3)
    assert n_mask.shape == (150,)
    assert bool(n_mask[0]) and bool(ca_mask[1]) and not bool(n_mask[1])
    assert int(n_mask.sum()) == 50 and int(ca_mask.sum()) == 50


def test_cloud_mask_atom_counts():
    # G=index 5 -> 4 atoms; W=index 18 -> 14 atoms; pad=20 -> 0 atoms
    seq = jnp.array([[5, 18, 20]])
    mask = scn_cloud_mask(seq)
    assert mask.shape == (1, 3, 14)
    assert int(mask[0, 0].sum()) == 4
    assert int(mask[0, 1].sum()) == 14
    assert int(mask[0, 2].sum()) == 0


def test_nerf_and_dihedral():
    # the reference's hand-computed geometry oracle (tests/test_utils.py:37-63)
    a = jnp.array([1.0, 2, 3])
    b = jnp.array([1.0, 4, 5])
    c = jnp.array([1.0, 4, 7])
    d = jnp.array([1.0, 8, 8])
    v1, v2, v3 = np.array(b - a), np.array(c - b), np.array(d - c)
    theta = np.arccos(v2 @ v3 / (np.linalg.norm(v2) * np.linalg.norm(v3)))
    n_p, n_p_ = np.cross(v1, v2), np.cross(v2, v3)
    chi = np.arccos(n_p @ n_p_ / (np.linalg.norm(n_p) * np.linalg.norm(n_p_)))
    l = jnp.asarray(np.linalg.norm(v3))
    rebuilt = nerf(a, b, c, l, jnp.asarray(theta), jnp.asarray(chi - np.pi))
    assert float(jnp.abs(rebuilt - jnp.array([1.0, 0, 6])).sum()) < 0.1
    assert np.isclose(float(get_dihedral(a, b, c, d)), chi, atol=1e-5)


def test_nerf_batched_matches_single():
    key = jax.random.key(1)
    pts = jax.random.normal(key, (8, 4, 3))
    l = jnp.ones((8,)) * 1.5
    theta = jnp.full((8,), 2.0)
    chi = jnp.full((8,), 0.7)
    batched = nerf(pts[:, 0], pts[:, 1], pts[:, 2], l, theta, chi)
    for i in range(8):
        single = nerf(pts[i, 0], pts[i, 1], pts[i, 2], l[i], theta[i], chi[i])
        assert np.allclose(batched[i], single, atol=1e-5)


def test_sidechain_container_shape():
    bb = jax.random.normal(jax.random.key(0), (2, 137 * 3, 3))
    proto = sidechain_container(bb, place_oxygen=True)
    assert proto.shape == (2, 137, 14, 3)
    # backbone slots preserved exactly
    assert np.allclose(proto[:, :, :3].reshape(2, -1, 3), bb, atol=1e-6)
    # non-oxygen sidechain slots are CA copies
    assert np.allclose(proto[:, :, 4], proto[:, :, 1], atol=1e-6)


def test_sidechain_container_oxygen_geometry():
    # O placed by NeRF should sit at the c-o bond length from C
    bb = jax.random.normal(jax.random.key(2), (1, 10 * 3, 3)) * 3.0
    proto = sidechain_container(bb, place_oxygen=True)
    c = proto[:, :, 2]
    o = proto[:, :, 3]
    dist = jnp.linalg.norm(o - c, axis=-1)
    assert np.allclose(dist, constants.BB_BUILD_INFO["BONDLENS"]["c-o"], atol=1e-4)


def test_sidechain_container_differentiable():
    bb = jax.random.normal(jax.random.key(3), (1, 6 * 3, 3))

    def loss(b):
        return jnp.sum(sidechain_container(b, place_oxygen=True) ** 2)

    g = jax.grad(loss)(bb)
    assert np.all(np.isfinite(g))
