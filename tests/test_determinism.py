"""Determinism tier (SURVEY.md S5.2): the reference's only determinism
machinery is RNG capture/replay for reversible recompute; here determinism
is end-to-end by construction (stateless PRNG keys, deterministic data
seeds, ordered native prefetch) — and these tests pin it."""

import pytest
import jax
import numpy as np

from alphafold2_tpu.config import Config, DataConfig, ModelConfig, TrainConfig
from alphafold2_tpu.data.pipeline import SyntheticDataset
from alphafold2_tpu.train.loop import (
    build_model,
    device_put_batch,
    init_state,
    make_train_step,
)


def _cfg():
    return Config(
        model=ModelConfig(dim=32, depth=1, heads=2, dim_head=16, max_seq_len=64,
                          attn_dropout=0.1, ff_dropout=0.1, bfloat16=False),
        data=DataConfig(crop_len=16, msa_depth=2, msa_len=16, batch_size=2,
                        min_len_filter=8),
        train=TrainConfig(gradient_accumulate_every=1, warmup_steps=2, seed=3),
    )


def _run(n_steps=3):
    cfg = _cfg()
    ds = iter(SyntheticDataset(cfg.data, seed=cfg.train.seed))
    model = build_model(cfg)
    state = init_state(cfg, model, next(iter(SyntheticDataset(cfg.data, seed=0))))
    step = make_train_step(model)
    rng = jax.random.key(cfg.train.seed)
    losses = []
    for _ in range(n_steps):
        rng, r = jax.random.split(rng)
        state, metrics = step(state, device_put_batch(next(ds)), r)
        losses.append(float(metrics["loss"]))
    return losses, state


@pytest.mark.slow
def test_training_run_bitwise_repeatable():
    # dropout active (attn+ff 0.1), real data stream: two runs from the same
    # seeds must produce bit-identical loss trajectories and final params
    l1, s1 = _run()
    l2, s2 = _run()
    assert l1 == l2, (l1, l2)
    for a, b in zip(jax.tree.leaves(s1.params), jax.tree.leaves(s2.params)):
        assert np.array_equal(np.asarray(a), np.asarray(b))


def test_mds_deterministic_by_key():
    from alphafold2_tpu.utils.mds import mds

    d = np.abs(np.random.default_rng(0).normal(size=(1, 12, 12))).astype(
        np.float32
    )
    d = d + d.transpose(0, 2, 1)
    c1, _ = mds(d, iters=20, key=jax.random.key(5))
    c2, _ = mds(d, iters=20, key=jax.random.key(5))
    c3, _ = mds(d, iters=20, key=jax.random.key(6))
    assert np.array_equal(np.asarray(c1), np.asarray(c2))
    assert not np.allclose(np.asarray(c1), np.asarray(c3))
