"""Async serving frontend tests (serve/scheduler.py + cache.py + faults.py).

The scheduling logic is tested deterministically: a fake clock plus
``start=False`` (the dispatcher is pumped inline, no thread) pins dwell
expiry vs batch fill, deadline misses, bounded-queue rejection, load
shedding and dedup without a single sleep. The real-engine tests then
prove the integration contracts: cached results byte-identical to direct
``predict_many`` output, and an injected dispatch failure yielding
retried-success instead of an exception to the caller."""

import dataclasses

import numpy as np
import pytest

from alphafold2_tpu.config import (
    Config,
    DataConfig,
    ModelConfig,
    ServeConfig,
)
from alphafold2_tpu.observe import EventCounters, Tracer
from alphafold2_tpu.serve import (
    AsyncServeFrontend,
    FaultPlan,
    InjectedFault,
    ResultCache,
    ServeEngine,
    ServeRequest,
    ServeResult,
)


def _cfg(buckets=(8, 16), max_batch=2, **serve_kw):
    serve_kw.setdefault("mds_iters", 10)
    return Config(
        model=ModelConfig(dim=32, depth=1, heads=2, dim_head=16,
                          max_seq_len=3 * max(buckets), bfloat16=False),
        data=DataConfig(msa_depth=2),
        serve=ServeConfig(buckets=buckets, max_batch=max_batch, **serve_kw),
    )


class FakeClock:
    def __init__(self):
        self.now = 100.0

    def __call__(self):
        return self.now

    def advance(self, dt):
        self.now += dt


class FakeEngine:
    """Engine stand-in for deterministic scheduler tests: records every
    dispatch, optionally fails the first N, never touches jax."""

    def __init__(self, cfg, fail_first=0):
        self.cfg = cfg
        self.buckets = cfg.serve.buckets
        self.max_batch = cfg.serve.max_batch
        self.mesh_desc = None  # single-device stand-in (no mesh identity)
        self.counters = EventCounters()
        self.tracer = Tracer(enabled=False)
        self.dispatched = []  # (bucket, [seq, ...]) per dispatch
        self._fail_remaining = fail_first

    def batch_for(self, bucket):
        return self.max_batch

    def dispatch_batch(self, bucket, reqs):
        self.dispatched.append((bucket, [r.seq for r in reqs]))
        if self._fail_remaining > 0:
            self._fail_remaining -= 1
            return [
                ServeResult(seq=r.seq, bucket=bucket, status="error",
                            error="InjectedFault: boom")
                for r in reqs
            ]
        return [
            ServeResult(
                seq=r.seq, bucket=bucket,
                atom14=np.zeros((len(r.seq), 14, 3), np.float32),
                latency_s=1e-3,
            )
            for r in reqs
        ]

    def retry_bucket(self, bucket):
        i = self.buckets.index(bucket)
        return self.buckets[i + 1] if i + 1 < len(self.buckets) else None


def _frontend(fail_first=0, **serve_kw):
    serve_kw.setdefault("dwell_ms", 50.0)
    eng = FakeEngine(_cfg(**serve_kw), fail_first=fail_first)
    clock = FakeClock()
    fe = AsyncServeFrontend(eng, clock=clock, start=False)
    return fe, eng, clock


# ----------------------------------------------------- dwell vs batch fill


def test_full_batch_dispatches_without_dwell():
    fe, eng, clock = _frontend()
    h1, h2 = fe.submit("ACDEFG"), fe.submit("MKVLIT")
    assert fe.pump() == 1  # batch filled to max_batch: no dwell wait
    assert eng.dispatched == [(8, ["ACDEFG", "MKVLIT"])]
    assert h1.result(0).ok and h2.result(0).ok


def test_partial_batch_waits_for_dwell_then_dispatches():
    fe, eng, clock = _frontend(dwell_ms=50.0)
    h = fe.submit("ACDEFG")
    assert fe.pump() == 0  # under-full and dwell not yet expired
    assert not h.done()
    clock.advance(0.049)
    assert fe.pump() == 0  # still inside the dwell window
    clock.advance(0.002)
    assert fe.pump() == 1  # dwell expired: dispatch partial
    assert eng.dispatched == [(8, ["ACDEFG"])]
    assert h.result(0).ok


def test_buckets_batch_independently():
    fe, eng, clock = _frontend()
    fe.submit("ACDEFG")  # bucket 8
    fe.submit("ACDEFGHKLMNP")  # bucket 16
    assert fe.pump() == 0  # neither bucket is full
    clock.advance(0.051)
    assert fe.pump() == 2  # both dwell-expire into partial dispatches
    assert sorted(b for b, _ in eng.dispatched) == [8, 16]


# ---------------------------------------------------------------- deadline


def test_deadline_miss_is_structured_and_never_dispatches():
    fe, eng, clock = _frontend(dwell_ms=10_000.0)
    h = fe.submit("ACDEFG", deadline_s=0.2)
    clock.advance(0.3)
    assert fe.pump() == 0
    r = h.result(0)
    assert r.status == "deadline_exceeded" and not r.ok
    assert r.atom14 is None and "deadline" in r.error
    assert r.queue_wait_s == pytest.approx(0.3)
    assert eng.dispatched == []
    assert fe.stats()["sched.deadline_miss"] == 1


def test_default_deadline_from_config():
    fe, eng, clock = _frontend(dwell_ms=10_000.0, default_deadline_s=0.1)
    h = fe.submit("ACDEFG")
    clock.advance(0.2)
    fe.pump()
    assert h.result(0).status == "deadline_exceeded"


def test_deadline_not_missed_when_dispatched_in_time():
    fe, eng, clock = _frontend()
    h = fe.submit("ACDEFG", deadline_s=1.0)
    fe.submit("MKVLIT")
    assert fe.pump() == 1
    assert h.result(0).ok


# ------------------------------------------------------- admission control


def test_bounded_queue_rejects_with_retry_after():
    fe, eng, clock = _frontend(
        queue_depth=2, dwell_ms=10_000.0, shed_watermark=0.0
    )
    handles = [fe.submit(s, priority=1)
               for s in ("ACDE", "MKVL", "GHKL")]
    assert handles[0].done() is False and handles[1].done() is False
    r = handles[2].result(0)  # third arrival: queue full, never queued
    assert r.status == "rejected" and "queue full" in r.error
    assert r.retry_after_s is not None and r.retry_after_s > 0
    assert eng.dispatched == []
    s = fe.stats()
    assert s["sched.rejected"] == 1 and s["sched.admitted"] == 2


def test_load_shedding_at_watermark_spares_high_priority():
    fe, eng, clock = _frontend(
        queue_depth=4, dwell_ms=10_000.0, shed_watermark=0.5
    )
    assert not fe.submit("ACDE").done()  # depth 1 <= watermark(2)
    assert not fe.submit("MKVL").done()  # depth 2 == watermark
    shed = fe.submit("GHKL")  # depth would cross the watermark
    r = shed.result(0)
    assert r.status == "rejected" and "shed" in r.error
    vip = fe.submit("WYTS", priority=1)  # high priority rides through
    assert not vip.done()
    s = fe.stats()
    assert s["sched.shed"] == 1 and s["sched.rejected"] == 1
    assert s["sched.admitted"] == 3


def test_unservable_requests_reject_structurally():
    fe, eng, clock = _frontend()  # largest bucket 16
    r = fe.submit("A" * 40).result(0)
    assert r.status == "rejected" and "unservable" in r.error
    r = fe.submit("").result(0)
    assert r.status == "rejected"
    assert eng.dispatched == []


def test_close_resolves_queued_requests():
    fe, eng, clock = _frontend(dwell_ms=10_000.0)
    h = fe.submit("ACDEFG")
    fe.close()
    r = h.result(0)
    assert r.status == "rejected" and "closed" in r.error


# --------------------------------------------------------- cache and dedup


def test_inflight_dedup_shares_one_dispatch():
    fe, eng, clock = _frontend()
    h1 = fe.submit(ServeRequest("ACDEFG", seed=7))
    h2 = fe.submit(ServeRequest("ACDEFG", seed=7))  # identical key: follower
    assert fe.pump() == 0  # ONE queue entry: batch is not full
    clock.advance(0.051)
    assert fe.pump() == 1
    assert len(eng.dispatched) == 1
    r1, r2 = h1.result(0), h2.result(0)
    assert r1.ok and r2.ok
    assert r2.cache_hit and r2.atom14 is r1.atom14  # the same arrays
    assert fe.stats()["sched.inflight_dedup"] == 1


def test_result_cache_hit_skips_queue_entirely():
    fe, eng, clock = _frontend(
        queue_depth=1, dwell_ms=10_000.0, shed_watermark=0.0
    )
    h1 = fe.submit(ServeRequest("ACDEFG", seed=7))
    fe.submit("MKVLIT")  # queue (depth 1) is full: structured rejection
    clock.advance(11.0)
    fe.pump()
    assert h1.result(0).ok
    # repeat of a completed key resolves instantly — even with the queue
    # full, admission control never touches a cache hit
    fe.submit("XXXX")  # occupies the queue again
    h3 = fe.submit(ServeRequest("ACDEFG", seed=7))
    r3 = h3.result(0)
    assert r3.ok and r3.cache_hit
    assert len(eng.dispatched) == 1
    assert fe.stats()["sched.cache_hits"] == 1


def test_distinct_seeds_do_not_dedup():
    fe, eng, clock = _frontend()
    fe.submit(ServeRequest("ACDEFG", seed=1))
    fe.submit(ServeRequest("ACDEFG", seed=2))
    assert fe.pump() == 1  # two distinct keys fill the batch
    assert eng.dispatched == [(8, ["ACDEFG", "ACDEFG"])]


def test_result_cache_lru_eviction_and_inflight_table():
    cache = ResultCache(capacity=2)
    status, entry = cache.lookup_or_claim("a")
    assert status == "leader"
    assert cache.lookup_or_claim("a", follower_ctx="ctx")[0] == "follower"
    assert cache.fulfill("a", "ra") == ["ctx"]
    for key, res in (("b", "rb"), ("c", "rc")):
        assert cache.lookup_or_claim(key)[0] == "leader"
        cache.fulfill(key, res)
    assert cache.peek("a") is None  # LRU evicted by b, c
    assert cache.lookup_or_claim("c")[0] == "hit"
    # failures must not be cached (cache=False) but still fan out
    assert cache.lookup_or_claim("d")[0] == "leader"
    cache.fulfill("d", "err", cache=False)
    assert cache.peek("d") is None
    # capacity 0 disables the LRU but dedup still works
    nocache = ResultCache(capacity=0)
    assert nocache.lookup_or_claim("x")[0] == "leader"
    assert nocache.lookup_or_claim("x")[0] == "follower"
    nocache.fulfill("x", "rx")
    assert nocache.lookup_or_claim("x")[0] == "leader"


def test_lru_eviction_with_attached_follower_never_orphans():
    # the in-flight table is separate from the LRU: churning the LRU to
    # capacity while a leader is still queued with a follower attached
    # must not detach the follower — when the leader finally dispatches,
    # both handles resolve with the same arrays
    fe, eng, clock = _frontend(cache_size=1, dwell_ms=10_000.0)
    h1 = fe.submit(ServeRequest("ACDEFG", seed=7))  # leader, stays queued
    h2 = fe.submit(ServeRequest("ACDEFG", seed=7))  # follower attached
    assert fe.pump() == 0  # bucket 8 under-full, dwell huge: in-flight
    # two bucket-16 requests fill and complete: with capacity 1 the second
    # completion EVICTS the first — LRU churn while the follower waits
    fe.submit("ACDEFGHKLMNP")
    fe.submit("WWWWWWWWWWWW")
    assert fe.pump() == 1
    st = fe.cache.stats()
    assert st["entries"] == 1 and st["inflight"] == 1
    clock.advance(10.1)
    assert fe.pump() == 1  # leader's dwell expires: dispatch
    r1, r2 = h1.result(0), h2.result(0)
    assert r1.ok and r2.ok
    assert r2.cache_hit and r2.atom14 is r1.atom14  # follower resolved
    assert fe.stats()["sched.inflight_dedup"] == 1
    assert fe.cache.stats()["inflight"] == 0  # nothing left dangling


# ------------------------------------------------------------ fault + retry


def test_injected_failure_is_retried_on_next_rung():
    fe, eng, clock = _frontend(fail_first=1)
    h1, h2 = fe.submit("ACDEFG"), fe.submit("MKVLIT")
    assert fe.pump() == 1
    # first dispatch at bucket 8 failed; retry ran at rung 16
    assert [b for b, _ in eng.dispatched] == [8, 16]
    r1, r2 = h1.result(0), h2.result(0)
    assert r1.ok and r2.ok
    assert r1.retried and r2.retried
    assert fe.stats()["sched.retries"] == 2


def test_retry_exhaustion_delivers_structured_error():
    fe, eng, clock = _frontend(fail_first=2)  # retry fails too
    h = fe.submit("ACDEFG")
    fe.submit("MKVLIT")
    fe.pump()
    r = h.result(0)
    assert r.status == "error" and "boom" in r.error
    assert r.retried  # the delivered result is the retry's


def test_retry_disabled_by_config():
    fe, eng, clock = _frontend(fail_first=1, retry_failed=False)
    fe.submit("ACDEFG")
    h = fe.submit("MKVLIT")
    fe.pump()
    assert h.result(0).status == "error"
    assert len(eng.dispatched) == 1


def test_fault_plan_matching_and_spec():
    plan = FaultPlan(fail_dispatch=2, times=1)
    plan.on_dispatch(1, 8)  # no match
    with pytest.raises(InjectedFault):
        plan.on_dispatch(2, 8)
    plan.on_dispatch(2, 8)  # budget (times=1) exhausted: inert
    assert plan.fired == [{"dispatch": 2, "bucket": 8}]

    plan = FaultPlan.from_spec("bucket=16,times=2,delay=0,fail=1")
    assert plan.fail_bucket == 16 and plan.times == 2 and plan.fail
    delay_only = FaultPlan.from_spec("dispatch=1,fail=0")
    delay_only.on_dispatch(1, 8)  # delay-only plans never raise
    assert delay_only.fired
    assert FaultPlan.from_spec(None) is None
    assert FaultPlan.from_spec("") is None
    with pytest.raises(ValueError, match="unknown fault-spec key"):
        FaultPlan.from_spec("nope=1")


# ---------------------------------------------------- real-engine contracts


@pytest.fixture(scope="module")
def engine():
    return ServeEngine(_cfg())


def test_cached_result_byte_identical_to_predict_many(engine):
    """Acceptance criterion: a frontend-cached result must be
    byte-identical to an uncached direct predict_many of the same
    (seq, seed) — caching can never change what a caller receives."""
    direct = engine.predict_many([ServeRequest("ACDEFG", seed=3)])[0]
    with AsyncServeFrontend(engine) as fe:
        first = fe.submit(ServeRequest("ACDEFG", seed=3)).result(120)
        cached = fe.submit(ServeRequest("ACDEFG", seed=3)).result(10)
    assert first.ok and cached.ok and cached.cache_hit
    assert cached.atom14.tobytes() == direct.atom14.tobytes()
    assert cached.backbone.tobytes() == direct.backbone.tobytes()
    assert cached.weights.tobytes() == direct.weights.tobytes()
    assert fe.stats()["sched.cache_hits"] == 1


def test_real_engine_fault_retry_success(engine):
    """One injected dispatch failure yields retried-success for the
    caller — never an exception."""
    plan = FaultPlan(fail_bucket=8, times=1)
    eng = ServeEngine(_cfg(), params=engine.params, faults=plan)
    with AsyncServeFrontend(eng) as fe:
        r = fe.submit("ACDEFG").result(180)
    assert r.ok and r.retried
    assert r.bucket == 16  # retried on the next rung's executable
    assert plan.fired == [{"dispatch": 1, "bucket": 8}]
    s = eng.stats()
    assert s["serve.dispatch_errors"] == 1 and s["sched.retries"] == 1
    assert np.all(np.isfinite(r.atom14))


@pytest.mark.parametrize("stage", ["transfer", "compute", "fetch"])
def test_stage_fault_retried_success_per_stage(engine, stage):
    """Satellite contract: a fault injected into each pipeline stage
    (host device_put, executable call, result device_get) still yields
    retried-success for the caller — the stage knob proves the error
    routing works wherever the failure lands, not just pre-featurize."""
    plan = FaultPlan(fail_bucket=8, times=1, fail_stage=stage)
    eng = ServeEngine(_cfg(), params=engine.params, faults=plan)
    with AsyncServeFrontend(eng) as fe:
        r = fe.submit("ACDEFG").result(180)
    assert r.ok and r.retried
    assert r.bucket == 16  # retried on the next rung's executable
    assert plan.fired == [{"dispatch": 1, "bucket": 8, "stage": stage}]
    s = eng.stats()
    assert s["serve.dispatch_errors"] == 1 and s["sched.retries"] == 1
    assert np.all(np.isfinite(r.atom14))


def test_fault_stage_spec_parsing_and_validation():
    plan = FaultPlan.from_spec("bucket=8,times=1,stage=compute")
    assert plan.fail_stage == "compute"
    plan.on_dispatch(1, 8)  # staged plans are inert at the legacy hook
    assert plan.fired == []
    plan.on_stage("transfer", 1, 8)  # wrong stage: passes through
    with pytest.raises(InjectedFault, match="at compute"):
        plan.on_stage("compute", 1, 8)
    assert plan.fired == [{"dispatch": 1, "bucket": 8, "stage": "compute"}]
    plan.on_stage("compute", 2, 8)  # budget exhausted: inert
    with pytest.raises(ValueError, match="fail_stage"):
        FaultPlan(fail_bucket=8, fail_stage="nope")


def test_threaded_frontend_end_to_end(engine):
    """Background-dispatcher smoke on the real engine: mixed lengths and
    duplicates all resolve ok through the live thread."""
    reqs = ["ACDEFG", "MKVLIT", "ACDEFGHKLMNP", "ACDEFG", "WY"]
    with AsyncServeFrontend(engine) as fe:
        handles = [fe.submit(ServeRequest(s, seed=1)) for s in reqs]
        results = [h.result(180) for h in handles]
    assert all(r.ok for r in results)
    for seq, r in zip(reqs, results):
        assert r.seq == seq and r.atom14.shape == (len(seq), 14, 3)
    assert fe.histograms["queue_depth"].count >= 1


# --------------------------------------------- engine satellites (PR fixes)


def test_per_request_arrival_queue_wait(engine):
    """A request carrying its own arrival stamp gets its own queue-wait;
    the stream-level fallback keeps working beside it."""
    import time

    old = ServeRequest("ACDEFG", seed=1,
                       arrival_s=time.perf_counter() - 5.0)
    fresh = ServeRequest("MKVLIT", seed=2)
    r_old, r_fresh = engine.predict_many([old, fresh])
    assert r_old.queue_wait_s >= 4.9  # honored its own (older) arrival
    assert r_fresh.queue_wait_s < 2.0  # stream arrival, not the stale one
    assert r_old.latency_s == pytest.approx(
        r_old.queue_wait_s + r_old.dispatch_s
    )


def test_dispatch_error_yields_structured_results(engine):
    """Engine hardening: a mid-dispatch exception becomes per-request
    error results (no Nones, no raise), and the plan's budget expiry lets
    the very next call succeed."""
    plan = FaultPlan(fail_bucket=8, times=1)
    eng = ServeEngine(_cfg(), params=engine.params, faults=plan)
    out = eng.predict_many(["ACDEFG", "MK"])
    assert [r.status for r in out] == ["error", "error"]
    assert all("InjectedFault" in r.error for r in out)
    assert all(r.atom14 is None for r in out)
    assert eng.stats()["serve.dispatch_errors"] == 1
    ok = eng.predict_many(["ACDEFG"])[0]
    assert ok.ok and ok.error is None


def test_serve_result_dataclass_defaults():
    r = ServeResult(seq="AC", bucket=8, status="rejected",
                    error="queue full", retry_after_s=0.5)
    assert not r.ok and r.atom14 is None and r.retry_after_s == 0.5
    r2 = dataclasses.replace(r, status="ok")
    assert r2.ok
