"""Prediction-pipeline tests: sequence encoding, distogram realization, the
full predict() flow (random init), checkpoint restore, and PDB export."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from alphafold2_tpu.config import Config, DataConfig, ModelConfig, TrainConfig
from alphafold2_tpu.predict import (
    Prediction,
    encode_sequence,
    predict,
    realize_structure,
    synthesize_msa,
)


def tiny_cfg():
    return Config(
        model=ModelConfig(dim=32, depth=1, heads=2, dim_head=16, max_seq_len=64,
                          bfloat16=False),
        data=DataConfig(crop_len=8, msa_depth=2, msa_len=8, batch_size=1,
                        min_len_filter=8),
        train=TrainConfig(gradient_accumulate_every=1, warmup_steps=2),
    )


def test_encode_sequence():
    toks = encode_sequence("ACDy X")
    assert toks.shape == (1, 6)
    assert toks[0, 0] == 0 and toks[0, 1] == 1  # A, C
    assert toks[0, 3] == 19  # lowercase y -> Y
    assert toks[0, 4] == 20 and toks[0, 5] == 20  # unknown -> pad index


def test_synthesize_msa_mutates():
    seq = encode_sequence("ACDEFGHIKLMNPQRSTVWY")
    msa = synthesize_msa(seq, depth=4, seed=0)
    assert msa.shape == (1, 4, 20)
    assert (msa != np.repeat(seq[:, None], 4, axis=1)).any()


def test_realize_structure_from_sharp_distogram():
    # logits sharply peaked at the true distance bin must reconstruct the
    # structure up to rigid motion + chirality. The cloud must be COMPACT:
    # the distogram spans 2-20 A, pairs beyond get weight 0, and MDS cannot
    # fold a structure whose diameter far exceeds the observable range.
    from alphafold2_tpu.utils import Kabsch, TMscore, cdist
    from alphafold2_tpu.utils.structure import DISTANCE_THRESHOLDS

    ca = np.random.default_rng(0).uniform(-7, 7, size=(24, 3)).astype(
        np.float32
    ).T  # (3, N), diameter < 19.5 A
    dist = np.asarray(cdist(ca.T[None], ca.T[None]))[0]
    centers = DISTANCE_THRESHOLDS - 0.25
    bins = np.abs(dist[..., None] - centers[None, None]).argmin(-1)
    logits = jnp.asarray(
        20.0 * (np.arange(37)[None, None] == bins[..., None]), jnp.float32
    )[None]
    coords, _, weights = realize_structure(logits, iters=300, fix_mirror=False)
    rec = np.asarray(coords)[0]
    best = -1.0
    for cand in (rec, rec * np.asarray([[1.0], [1.0], [-1.0]], np.float32)):
        a, b = Kabsch(cand, ca)
        best = max(best, float(TMscore(np.asarray(a), np.asarray(b))[0]))
    assert best > 0.75, best
    assert np.asarray(weights).mean() > 0.1


def test_predict_random_init_exports_pdb(tmp_path):
    from alphafold2_tpu.utils import pdb as pdbio

    seq = "ACDEFGHK"
    pred = predict(tiny_cfg(), seq)
    assert isinstance(pred, Prediction)
    assert pred.atom14.shape == (8, 14, 3)
    assert pred.backbone.shape == (8, 3, 3)
    assert np.all(np.isfinite(pred.atom14))
    s = pred.to_pdb(seq)
    path = str(tmp_path / "pred.pdb")
    pdbio.save_pdb(s, path)
    back = pdbio.load_pdb(path)
    got_seq, ca = back.ca_trace()
    assert got_seq == seq
    assert np.allclose(ca, pred.backbone[:, 1], atol=1e-3)


def test_predict_validates_length_and_msa_depth():
    cfg = tiny_cfg()  # max_seq_len=64 -> at most 21 residues (3L tokens)
    with pytest.raises(ValueError, match="max_seq_len"):
        predict(cfg, "A" * 30)
    with pytest.raises(ValueError, match="MAX_NUM_MSA"):
        predict(cfg, "ACDEFGHK", msa_depth=99)


def test_predict_checkpoint_restore(tmp_path):
    from alphafold2_tpu.data.pipeline import SyntheticDataset
    from alphafold2_tpu.train.checkpoint import CheckpointManager
    from alphafold2_tpu.train.end2end import (
        End2EndModel, init_end2end_state,
    )

    cfg = tiny_cfg()
    model = End2EndModel(dim=32, depth=1, heads=2, dim_head=16, max_seq_len=64)
    batch = next(iter(SyntheticDataset(cfg.data, seed=0)))
    state = init_end2end_state(cfg, model, batch)
    ckpt = str(tmp_path / "ckpt")
    mgr = CheckpointManager(ckpt)
    mgr.save(5, state)
    mgr.wait()
    mgr.close()
    pred = predict(cfg, "ACDEFGHK", checkpoint_dir=ckpt)
    assert np.all(np.isfinite(pred.atom14))
    with pytest.raises(FileNotFoundError, match="no checkpoint"):
        predict(cfg, "ACDEFGHK", checkpoint_dir=str(tmp_path / "empty"))
