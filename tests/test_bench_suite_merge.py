"""bench_suite subset runs must MERGE into BENCH_SUITE.json (VERDICT r3 #6:
a partial TPU session re-running one config must not clobber the other
rows), but only when rows are comparable (same device, same smoke flag)."""

import importlib
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "scripts"))


def _rows(vals):
    return [{"config": f"{k}: cfg", "step_ms": v, "pairs_per_sec": 1.0}
            for k, v in vals.items()]


def _read(path):
    with open(path) as f:
        return json.load(f)


def _suite():
    return importlib.import_module("bench_suite")


def test_subset_merges_into_existing(tmp_path):
    mod = _suite()
    path = str(tmp_path / "BENCH_SUITE.json")
    mod.write_results(path, _rows({k: 1.0 for k in "12345"}),
                      "cpu", True, partial=False)
    # re-run only config 3: the other four rows must survive, 3 updates
    mod.write_results(path, _rows({"3": 99.0}), "cpu", True, partial=True)
    out = _read(path)
    assert [r["config"][0] for r in out["results"]] == list("12345")
    assert next(r for r in out["results"]
                if r["config"][0] == "3")["step_ms"] == 99.0
    assert next(r for r in out["results"]
                if r["config"][0] == "1")["step_ms"] == 1.0


def test_full_run_replaces_wholesale(tmp_path):
    mod = _suite()
    path = str(tmp_path / "BENCH_SUITE.json")
    mod.write_results(path, _rows({k: 1.0 for k in "12345"}),
                      "cpu", True, partial=False)
    mod.write_results(path, _rows({"3": 2.0}), "cpu", True, partial=False)
    out = _read(path)
    assert len(out["results"]) == 1  # full run = authoritative


def test_device_change_replaces_not_merges(tmp_path):
    # each comparability guard in isolation: a regression dropping either
    # the device check or the smoke check must fail one of these
    mod = _suite()
    path = str(tmp_path / "BENCH_SUITE.json")
    mod.write_results(path, _rows({k: 1.0 for k in "12345"}),
                      "cpu", True, partial=False)
    # same smoke, different device: no merge
    mod.write_results(path, _rows({"2": 5.0}), "TPU v5 lite", True,
                      partial=True)
    out = _read(path)
    assert out["device"] == "TPU v5 lite"
    assert len(out["results"]) == 1

    mod.write_results(path, _rows({k: 1.0 for k in "12345"}),
                      "cpu", True, partial=False)
    # same device, different smoke: no merge
    mod.write_results(path, _rows({"2": 5.0}), "cpu", False, partial=True)
    out = _read(path)
    assert out["smoke"] is False
    assert len(out["results"]) == 1


def test_unreadable_prior_file_survives(tmp_path):
    mod = _suite()
    path = str(tmp_path / "BENCH_SUITE.json")
    with open(path, "w") as f:
        f.write("{not json")
    mod.write_results(path, _rows({"2": 5.0}), "cpu", True, partial=True)
    assert _read(path)["results"][0]["step_ms"] == 5.0
