"""torch-matched init (models/init.py): distribution + wiring tests.

The reference trains torch module defaults (alphafold2.py:354-361,
train_pre.py:52-57); torch_match_reinit must reproduce those distributions
— checked analytically AND against torch's own reset_parameters draws —
while leaving LayerNorm at ones/zeros and preserving tree structure/dtypes.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from alphafold2_tpu.config import Config, DataConfig, ModelConfig
from alphafold2_tpu.models.init import torch_match_reinit
from alphafold2_tpu.train.loop import build_model, init_state, tiny_init_state


def _flat(params):
    return {
        "/".join(str(k.key) for k in path): leaf
        for path, leaf in jax.tree_util.tree_flatten_with_path(params)[0]
    }


@pytest.fixture(scope="module")
def reinit_pair():
    cfg = Config(
        model=ModelConfig(
            dim=64, depth=1, heads=4, dim_head=16, max_seq_len=64,
            msa_tie_row_attn=True, bfloat16=False,
        ),
        data=DataConfig(crop_len=24, msa_depth=4, msa_len=24, batch_size=1),
    )
    model = build_model(cfg)
    state = tiny_init_state(cfg, model)
    new = torch_match_reinit(state.params, jax.random.key(0))
    return state.params, new


def test_structure_and_dtype_preserved(reinit_pair):
    old, new = reinit_pair
    assert jax.tree_util.tree_structure(old) == jax.tree_util.tree_structure(new)
    for (pa, a), (pb, b) in zip(
        jax.tree_util.tree_flatten_with_path(old)[0],
        jax.tree_util.tree_flatten_with_path(new)[0],
    ):
        assert pa == pb and a.shape == b.shape and a.dtype == b.dtype


def test_embedding_is_standard_normal(reinit_pair):
    _, new = reinit_pair
    flat = _flat(new)
    embs = np.concatenate([
        np.asarray(v).ravel() for k, v in flat.items() if "embedding" in k
    ])
    # flax default would give std 1/sqrt(64) = 0.125; torch N(0,1) ~ 1.0
    assert 0.97 < embs.std() < 1.03, embs.std()
    assert abs(embs.mean()) < 0.02


def test_dense_kernel_and_bias_are_bounded_uniform(reinit_pair):
    _, new = reinit_pair
    flat = _flat(new)
    checked = 0
    for k, v in flat.items():
        if not k.endswith("kernel") or "LayerNorm" in k:
            continue
        v = np.asarray(v)
        fan_in = int(np.prod(v.shape[:-1]))
        bound = 1.0 / np.sqrt(fan_in)
        assert np.abs(v).max() <= bound * (1 + 1e-6), k
        # uniform(-b, b) std = b/sqrt(3); lecun-normal would be b at std
        assert abs(v.std() - bound / np.sqrt(3)) < 0.25 * bound, k
        bias_key = k.rsplit("/", 1)[0] + "/bias"
        if bias_key in flat:
            b = np.asarray(flat[bias_key])
            assert np.abs(b).max() <= bound * (1 + 1e-6), bias_key
            assert np.abs(b).sum() > 0, bias_key  # flax zeros replaced
        checked += 1
    assert checked >= 5  # attention qkv/out + ff wi/wo at minimum


def test_layernorm_untouched(reinit_pair):
    old, new = reinit_pair
    fo, fn = _flat(old), _flat(new)
    ln = [k for k in fn if "norm" in k.lower() and k.endswith(("scale", "bias"))]
    assert ln, "expected LayerNorm params in the tree"
    for k in ln:
        np.testing.assert_array_equal(np.asarray(fo[k]), np.asarray(fn[k]))


def test_matches_torch_moments():
    """Draw the same-shaped Linear/Embedding in torch and compare moments."""
    torch = pytest.importorskip("torch")
    torch.manual_seed(0)
    lin = torch.nn.Linear(64, 256)
    emb = torch.nn.Embedding(1000, 64)

    params = {
        "dense": {
            "kernel": jnp.zeros((64, 256)), "bias": jnp.zeros((256,)),
        },
        "embed": {"embedding": jnp.zeros((1000, 64))},
    }
    new = torch_match_reinit(params, jax.random.key(1))
    tw = lin.weight.detach().numpy()
    jw = np.asarray(new["dense"]["kernel"])
    assert abs(tw.std() - jw.std()) < 0.1 * tw.std()
    assert abs(np.abs(tw).max() - np.abs(jw).max()) < 0.05 * np.abs(tw).max()
    tb = lin.bias.detach().numpy()
    jb = np.asarray(new["dense"]["bias"])
    assert abs(tb.std() - jb.std()) < 0.2 * tb.std()
    te = emb.weight.detach().numpy()
    je = np.asarray(new["embed"]["embedding"])
    assert abs(te.std() - je.std()) < 0.05


def test_deterministic(reinit_pair):
    old, _ = reinit_pair
    a = torch_match_reinit(old, jax.random.key(7))
    b = torch_match_reinit(old, jax.random.key(7))
    for x, y in zip(jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
    c = torch_match_reinit(old, jax.random.key(8))
    diff = sum(
        float(jnp.abs(x - y).sum())
        for x, y in zip(jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(c))
    )
    assert diff > 0


def test_config_wiring_and_scan_guard():
    cfg = Config(
        model=ModelConfig(
            dim=32, depth=1, heads=2, dim_head=16, max_seq_len=64,
            bfloat16=False, init_scheme="torch",
        ),
        data=DataConfig(crop_len=16, msa_depth=2, msa_len=16, batch_size=1),
    )
    state = tiny_init_state(cfg, build_model(cfg))
    flat = _flat(state.params)
    tok = np.asarray(
        next(v for k, v in flat.items() if k.endswith("token_emb/embedding"))
    )
    assert 0.9 < tok.std() < 1.1  # torch N(0,1), not flax N(0, 1/32)

    import dataclasses

    bad = dataclasses.replace(
        cfg, model=dataclasses.replace(cfg.model, scan_layers=True)
    )
    with pytest.raises(ValueError, match="scan_layers"):
        tiny_init_state(bad, build_model(bad))

    # the reversible engine's vmap-stacked `layers` tree would inflate
    # fan_in by depth — must be rejected, not silently mis-drawn
    rev = dataclasses.replace(
        cfg, model=dataclasses.replace(cfg.model, reversible=True)
    )
    with pytest.raises(ValueError, match="reversible"):
        tiny_init_state(rev, build_model(rev))

    unk = dataclasses.replace(
        cfg, model=dataclasses.replace(cfg.model, init_scheme="xavier")
    )
    with pytest.raises(ValueError, match="init_scheme"):
        tiny_init_state(unk, build_model(unk))


def test_one_train_step_finite():
    """A torch-init model must actually train (finite loss/grads)."""
    import optax
    from flax.training.train_state import TrainState

    from alphafold2_tpu.data.pipeline import SyntheticDataset
    from alphafold2_tpu.train.loop import distogram_cross_entropy
    from alphafold2_tpu.utils.structure import get_bucketed_distance_matrix

    cfg = Config(
        model=ModelConfig(
            dim=32, depth=1, heads=2, dim_head=16, max_seq_len=64,
            bfloat16=False, init_scheme="torch",
        ),
        data=DataConfig(crop_len=16, msa_depth=2, msa_len=16, batch_size=1),
    )
    model = build_model(cfg)
    state = tiny_init_state(cfg, model)
    state = TrainState.create(
        apply_fn=model.apply, params=state.params, tx=optax.adam(3e-4)
    )
    batch = {
        k: jnp.asarray(v)
        for k, v in next(iter(SyntheticDataset(cfg.data, seed=0))).items()
    }

    def loss_fn(p):
        logits = state.apply_fn(
            p, batch["seq"], batch.get("msa"),
            mask=batch["mask"], msa_mask=batch.get("msa_mask"),
        )
        labels = get_bucketed_distance_matrix(batch["coords"], batch["mask"])
        return distogram_cross_entropy(logits, labels)

    ce, grads = jax.value_and_grad(loss_fn)(state.params)
    assert np.isfinite(float(ce))
    gnorm = optax.global_norm(grads)
    assert np.isfinite(float(gnorm)) and float(gnorm) > 0
