"""Graph-contract tests: fingerprints are deterministic, every contract
field change produces a readable diff line (op-count drift, recompile-key
input/treedef changes, donation changes), the check verdict machinery
mirrors observe.regress's explicit third states (stale/missing baseline),
and the CLI round-trips a baseline through --update/--check."""

import copy
import json
import os
import subprocess
import sys

import jax.numpy as jnp
import pytest

from alphafold2_tpu.analysis import contracts
from alphafold2_tpu.analysis.targets import TraceTarget

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def synthetic(name="syn", fn=None, args=None, donate=()):
    fn = fn if fn is not None else (lambda x: jnp.sin(x) * 2.0 + 1.0)
    args = args if args is not None else (jnp.ones((4, 4)),)
    return TraceTarget(
        name=name, build=lambda: (fn, args), donate_argnums=donate
    )


def test_fingerprint_is_deterministic():
    t = synthetic()
    a = contracts.fingerprint_target(t)
    b = contracts.fingerprint_target(t)
    assert a == b
    assert a["ops"].get("sin") == 1
    assert a["n_eqns"] == sum(a["ops"].values())
    assert a["inputs"] == ["float32[4, 4]"]


def test_compute_contracts_records_jax_version():
    import jax

    doc = contracts.compute_contracts([synthetic()])
    assert doc["jax_version"] == jax.__version__
    assert doc["format"] == contracts.FORMAT_VERSION
    assert set(doc["targets"]) == {"syn"}


# -------------------------------------------------------------------- diff


def _base_doc():
    return contracts.compute_contracts([synthetic()])


def test_identical_contracts_have_no_diff():
    doc = _base_doc()
    assert contracts.diff_contracts(doc, copy.deepcopy(doc)) == []


def test_op_count_drift_is_named_per_primitive():
    doc = _base_doc()
    drifted = copy.deepcopy(doc)
    drifted["targets"]["syn"]["ops"]["sin"] += 2
    drifted["targets"]["syn"]["ops"]["dot_general"] = 5
    lines = contracts.diff_contracts(doc, drifted)
    assert any("sin: 1 -> 3 (+2)" in l for l in lines), lines
    assert any("dot_general: 0 -> 5 (+5)" in l for l in lines), lines


def test_input_signature_change_is_a_recompile_key():
    doc = _base_doc()
    drifted = copy.deepcopy(doc)
    drifted["targets"]["syn"]["inputs"] = ["float32[8, 8]"]
    lines = contracts.diff_contracts(doc, drifted)
    assert any("RECOMPILE KEY" in l and "float32[8, 8]" in l for l in lines)


def test_treedef_donation_and_target_set_changes():
    doc = _base_doc()
    drifted = copy.deepcopy(doc)
    drifted["targets"]["syn"]["in_treedef"] = "PyTreeDef({'other': *})"
    drifted["targets"]["syn"]["donation"] = [0]
    drifted["targets"]["extra"] = drifted["targets"]["syn"]
    lines = contracts.diff_contracts(doc, drifted)
    assert any("treedef changed" in l for l in lines)
    assert any("donation map changed" in l for l in lines)
    assert any("extra: new target" in l for l in lines)
    removed = contracts.diff_contracts(drifted, doc)
    assert any("extra: target removed" in l for l in removed)


# ----------------------------------------------------------------- verdicts


def test_check_against_pass_drift_and_stale(tmp_path):
    t = synthetic()
    baseline = tmp_path / "graph_contracts.json"
    baseline.write_text(json.dumps(contracts.compute_contracts([t])))

    result = contracts.check_against(str(baseline), [t])
    assert result["verdict"] == "pass"
    assert result["diffs"] == []

    # synthetic op-count drift: the acceptance scenario the CI job gates
    doc = json.loads(baseline.read_text())
    doc["targets"]["syn"]["ops"]["sin"] = 99
    baseline.write_text(json.dumps(doc))
    result = contracts.check_against(str(baseline), [t])
    assert result["verdict"] == "drift"
    assert any("sin: 99 -> 1" in l for l in result["diffs"])

    # a baseline traced under another jax is stale, not a repo regression
    doc["jax_version"] = "0.0.1"
    baseline.write_text(json.dumps(doc))
    result = contracts.check_against(str(baseline), [t])
    assert result["verdict"] == "stale-baseline"
    assert "re-baseline" in result["reason"]


def test_missing_baseline_is_explicit(tmp_path):
    result = contracts.check_against(str(tmp_path / "nope.json"), [synthetic()])
    assert result["verdict"] == "missing-baseline"


# ------------------------------------------------------------ real targets


@pytest.mark.slow
def test_committed_contracts_hold():
    """The committed graph_contracts.json matches the code — the CI
    graph-contract job's in-suite twin (skips when the environment's jax
    differs from the baseline's, exactly like the CLI)."""
    result = contracts.check_against(contracts.DEFAULT_BASELINE)
    assert result["verdict"] in ("pass", "stale-baseline"), result
    if result["verdict"] == "pass":
        assert result["diffs"] == []


@pytest.mark.slow
def test_cli_update_check_roundtrip_and_drift_rc(tmp_path):
    """CLI round-trip on the real registry: --update writes a baseline
    --check accepts (rc 0); an injected op drift flips rc to 1 with the
    primitive named."""
    baseline = tmp_path / "contracts.json"
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    run = lambda *a: subprocess.run(
        [sys.executable, "-m", "alphafold2_tpu.analysis.contracts", *a],
        capture_output=True, text=True, timeout=900, env=env, cwd=REPO,
    )
    proc = run("--update", "--baseline", str(baseline))
    assert proc.returncode == 0, proc.stdout + proc.stderr
    proc = run("--check", "--baseline", str(baseline))
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "verdict=pass" in proc.stdout

    doc = json.loads(baseline.read_text())
    name = next(iter(doc["targets"]))
    prim = next(iter(doc["targets"][name]["ops"]))
    doc["targets"][name]["ops"][prim] += 7
    baseline.write_text(json.dumps(doc))
    proc = run("--check", "--baseline", str(baseline))
    assert proc.returncode == 1
    assert "DRIFT" in proc.stdout and prim in proc.stdout
