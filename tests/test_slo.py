"""SLO burn-rate monitor + metrics registry + Prometheus exposition
(observe/slo.py, registry.py, exposition.py). All clock-driven logic runs
on a fake clock — burn windows advance deterministically, no sleeps; the
HTTP endpoint is exercised live once on an ephemeral loopback port."""

import json
import urllib.request
from types import SimpleNamespace

import pytest

from alphafold2_tpu.observe import Tracer
from alphafold2_tpu.observe.exposition import (
    MetricsHTTPServer,
    render_prometheus,
    serve_from_env,
)
from alphafold2_tpu.observe.registry import MetricsRegistry
from alphafold2_tpu.observe.slo import (
    SLOMonitor,
    SLOSpec,
    default_serve_slos,
    parse_slo_specs,
    priority_class,
)


class FakeClock:
    def __init__(self):
        self.now = 1000.0

    def __call__(self):
        return self.now

    def advance(self, dt):
        self.now += dt


def _result(status="ok", latency_s=0.01):
    return SimpleNamespace(status=status, latency_s=latency_s)


# ------------------------------------------------------------------ specs


def test_spec_parsing_round_trip():
    spec = SLOSpec.from_str(
        "lat_hi,objective=latency,threshold_ms=500,target=0.95,class=high"
    )
    assert spec.name == "lat_hi" and spec.objective == "latency"
    assert spec.threshold_ms == 500.0 and spec.priority_class == "high"
    specs = parse_slo_specs(
        "a,objective=latency,threshold_ms=1;b,objective=error_rate"
    )
    assert [s.name for s in specs] == ["a", "b"]
    assert parse_slo_specs("") == []


def test_spec_validation():
    with pytest.raises(ValueError):
        SLOSpec(name="x", objective="nope")
    with pytest.raises(ValueError):
        SLOSpec(name="x", objective="latency")  # threshold required
    with pytest.raises(ValueError):
        SLOSpec(name="x", objective="availability", target=1.5)


def test_default_serve_slos_cover_classes_and_objectives():
    specs = default_serve_slos(deadline_s=30)
    names = {s.name for s in specs}
    assert {"latency_high", "latency_normal", "latency_low",
            "error_rate", "deadline_miss"} <= names
    assert priority_class(2) == "high"
    assert priority_class(0) == "normal"
    assert priority_class(-1) == "low"


# ---------------------------------------------------------------- monitor


def test_burn_rate_alert_fires_on_injected_latency():
    clock = FakeClock()
    registry = MetricsRegistry(clock=clock)
    tracer = Tracer(enabled=True)
    spec = SLOSpec(name="lat", objective="latency", threshold_ms=100,
                   target=0.95, min_events=10)
    mon = SLOMonitor([spec], registry=registry, clock=clock, tracer=tracer)

    # healthy traffic first: no alert
    for _ in range(20):
        mon.observe(_result(latency_s=0.01))
        clock.advance(0.5)
    (verdict,) = mon.evaluate()
    assert not verdict["alert"] and verdict["fast_burn"] == 0.0

    # injected latency fault: every request breaches the threshold
    for _ in range(20):
        mon.observe(_result(latency_s=0.5))
        clock.advance(0.5)
    (verdict,) = mon.evaluate()
    assert verdict["alert"], verdict
    assert verdict["fast_burn"] >= spec.burn_threshold
    assert verdict["slow_burn"] >= spec.burn_threshold
    # the structured alert event fired exactly once (one-shot per spec)
    mon.evaluate()
    alerts = [e for e in tracer.events() if e["name"] == "slo.alert"]
    assert len(alerts) == 1
    assert alerts[0]["args"]["spec"] == "lat"


def test_alert_needs_both_windows_burning():
    """A single fast-window spike with a clean slow window must NOT alert
    (the multi-window design exists to suppress blips)."""
    clock = FakeClock()
    registry = MetricsRegistry(clock=clock)
    spec = SLOSpec(name="lat", objective="latency", threshold_ms=100,
                   target=0.95, min_events=5, fast_window_s=10,
                   slow_window_s=300)
    mon = SLOMonitor([spec], registry=registry, clock=clock)
    # long healthy history fills the slow window with goods
    for _ in range(200):
        mon.observe(_result(latency_s=0.01))
        clock.advance(1.0)
    # short burst of bads: fast window saturates, slow window diluted
    for _ in range(8):
        mon.observe(_result(latency_s=0.5))
        clock.advance(0.5)
    (verdict,) = mon.evaluate()
    assert verdict["fast_burn"] >= spec.burn_threshold
    assert verdict["slow_burn"] < spec.burn_threshold
    assert not verdict["alert"]


def test_class_scoped_spec_ignores_other_classes():
    clock = FakeClock()
    mon = SLOMonitor(
        [SLOSpec(name="hi", objective="latency", threshold_ms=100,
                 priority_class="high", min_events=1)],
        registry=MetricsRegistry(clock=clock), clock=clock,
    )
    mon.observe(_result(latency_s=0.5), priority=0)  # normal: not counted
    (v,) = mon.evaluate()
    assert v["fast_events"] == 0
    mon.observe(_result(latency_s=0.5), priority=2)  # high: counted, bad
    (v,) = mon.evaluate()
    assert v["fast_events"] == 1 and v["fast_burn"] > 0


def test_rejections_excluded_from_error_rate_but_not_availability():
    clock = FakeClock()
    mon = SLOMonitor(
        [SLOSpec(name="err", objective="error_rate", min_events=1),
         SLOSpec(name="avail", objective="availability", min_events=1)],
        registry=MetricsRegistry(clock=clock), clock=clock,
    )
    mon.observe(_result(status="rejected"))
    err, avail = mon.evaluate()
    assert err["fast_events"] == 0  # never dispatched: not an error event
    assert avail["fast_events"] == 1 and avail["fast_burn"] > 0


# --------------------------------------------------------------- registry


def test_windowed_counter_sum_and_rate_with_fake_clock():
    clock = FakeClock()
    reg = MetricsRegistry(clock=clock)
    wc = reg.windowed_counter("hits")
    for _ in range(10):
        wc.add()
        clock.advance(1.0)
    assert wc.total == 10
    assert wc.sum(5) == pytest.approx(5, abs=1)
    clock.advance(1000.0)  # everything ages out of the windows
    assert wc.sum(5) == 0
    assert wc.total == 10  # lifetime total survives pruning


def test_windowed_values_percentiles():
    clock = FakeClock()
    reg = MetricsRegistry(clock=clock)
    wv = reg.windowed_values("lat")
    for v in range(1, 101):
        wv.observe(float(v))
    snap = wv.snapshot()
    assert snap["p50"] == pytest.approx(50, abs=2)
    assert snap["p99"] == pytest.approx(99, abs=2)
    assert snap["max"] == 100


def test_registry_snapshot_flattens_and_guards_kind():
    clock = FakeClock()
    reg = MetricsRegistry(clock=clock)
    reg.counter("n").inc(3)
    reg.gauge("depth").set(7)
    reg.windowed_counter("hits").add(2)
    reg.windowed_values("lat").observe(1.0)
    snap = reg.snapshot()
    assert snap["n"] == 3 and snap["depth"] == 7
    assert snap["hits.total"] == 2
    assert any(k.startswith("lat.p") for k in snap)
    with pytest.raises(ValueError):
        reg.gauge("n")  # name already registered as a counter


# ------------------------------------------------------------- exposition


def test_render_prometheus_format():
    text = render_prometheus(
        {"serve.latency_ms.p95": 12.5, "sched.admitted": 4,
         "9lives": 1, "skip_me": "not a number"}
    )
    lines = text.splitlines()
    assert "af2tpu_serve_latency_ms_p95 12.5" in lines
    assert "af2tpu_sched_admitted 4" in lines
    assert any(ln.startswith("# TYPE af2tpu_serve_latency_ms_p95")
               for ln in lines)
    assert not any("skip_me" in ln for ln in lines)
    assert any("_9lives" in ln for ln in lines)  # leading digit sanitized


def test_metrics_http_server_live():
    server = MetricsHTTPServer(
        lambda: {"sched.admitted": 42}, port=0
    ).start()
    try:
        base = f"http://127.0.0.1:{server.port}"
        body = urllib.request.urlopen(f"{base}/metrics", timeout=5).read()
        assert b"af2tpu_sched_admitted 42" in body
        health = json.loads(
            urllib.request.urlopen(f"{base}/healthz", timeout=5).read()
        )
        assert health["ok"] is True
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(f"{base}/nope", timeout=5)
    finally:
        server.stop()


def test_serve_from_env_disabled_when_unset(monkeypatch):
    monkeypatch.delenv("AF2TPU_METRICS_PORT", raising=False)
    assert serve_from_env(lambda: {}) is None
    monkeypatch.setenv("AF2TPU_METRICS_PORT", "")
    assert serve_from_env(lambda: {}) is None
