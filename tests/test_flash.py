"""Flash-attention wrapper gating tests. The fused kernel itself is the
stock JAX Pallas TPU op (compiled only on TPU backends; AF2TPU_TEST_TPU=1
runs these paths on hardware) — what is tested hermetically is the
gating/fallback contract the model relies on."""

import jax
import jax.numpy as jnp
import numpy as np

from alphafold2_tpu.ops.attention import Attention
from alphafold2_tpu.ops.flash import flash_attention, flash_available


def test_unavailable_off_tpu_returns_none():
    assert not flash_available()  # suite runs on the CPU backend
    q = jnp.ones((1, 2, 16, 8))
    assert flash_attention(q, q, q) is None


def test_attention_use_flash_true_falls_back_cleanly():
    # explicit use_flash=True off-TPU: wrapper returns None, dense path runs,
    # numbers identical to use_flash=False
    x = jax.random.normal(jax.random.key(0), (2, 24, 32))
    mask = jnp.ones((2, 24), bool).at[:, 20:].set(False)
    a_flash = Attention(dim=32, heads=2, dim_head=16, use_flash=True)
    a_dense = Attention(dim=32, heads=2, dim_head=16, use_flash=False)
    params = a_dense.init(jax.random.key(1), x, mask=mask)
    out_f = a_flash.apply(params, x, mask=mask)
    out_d = a_dense.apply(params, x, mask=mask)
    assert np.allclose(out_f, out_d, atol=1e-6)


def test_flash_skipped_for_tied_rows_and_dropout(monkeypatch):
    # tied rows and attn dropout are dense-path features; flash gating must
    # not change their outputs
    x = jax.random.normal(jax.random.key(2), (4, 8, 32))  # (B*R, n, d)
    a = Attention(dim=32, heads=2, dim_head=16, use_flash=True)
    b = Attention(dim=32, heads=2, dim_head=16, use_flash=False)
    params = b.init(jax.random.key(3), x, tie_dim=2)
    assert np.allclose(
        a.apply(params, x, tie_dim=2), b.apply(params, x, tie_dim=2), atol=1e-6
    )

    # dropout gate: with attn dropout active (deterministic=False), the flash
    # path must NOT be taken even when the kernel is "available" — attention-
    # weight dropout needs materialized probabilities
    from alphafold2_tpu.ops import flash as flash_mod

    def boom(*a, **kw):  # pragma: no cover - must not be reached
        raise AssertionError("flash path taken despite active attn dropout")

    drop = Attention(dim=32, heads=2, dim_head=16, dropout=0.5, use_flash=None)
    params_d = drop.init(jax.random.key(4), x)  # before the mock: init is deterministic
    monkeypatch.setattr(flash_mod, "flash_available", lambda: True)
    monkeypatch.setattr(flash_mod, "flash_attention", boom)
    out = drop.apply(
        params_d, x, deterministic=False, rngs={"dropout": jax.random.key(5)}
    )
    assert np.all(np.isfinite(out))
    # ...and with deterministic=True the (mocked) flash path IS selected
    with np.testing.assert_raises(AssertionError):
        drop.apply(params_d, x, deterministic=True)


def test_compressed_cross_attention_routes_through_flash(monkeypatch):
    """KV-compressed cross-attention composes with the fused kernel: the
    flash branch sees the already-compressed k/v and the pooled mask. At
    large crops this is what keeps the (N^2 queries x compressed keys)
    logits out of HBM (bench config 3)."""
    from alphafold2_tpu.ops import flash as flash_mod

    b, n, nc, d = 2, 12, 30, 32
    x = jax.random.normal(jax.random.key(6), (b, n, d))
    ctx = jax.random.normal(jax.random.key(7), (b, nc, d))
    cmask = jnp.ones((b, nc), bool).at[:, 25:].set(False)

    dense = Attention(dim=d, heads=2, dim_head=16, compress_ratio=3,
                      use_flash=False)
    params = dense.init(jax.random.key(8), x, context=ctx, context_mask=cmask)

    seen = {}

    def spy(q, k, v, q_mask=None, kv_mask=None, sm_scale=1.0):
        seen["kv_len"] = k.shape[2]
        seen["kv_mask"] = kv_mask
        return None  # fall back to dense — output must be unchanged

    monkeypatch.setattr(flash_mod, "flash_available", lambda: True)
    monkeypatch.setattr(flash_mod, "flash_attention", spy)
    flashy = Attention(dim=d, heads=2, dim_head=16, compress_ratio=3,
                       use_flash=True)
    out_f = flashy.apply(params, x, context=ctx, context_mask=cmask)
    out_d = dense.apply(params, x, context=ctx, context_mask=cmask)

    assert seen["kv_len"] == nc // 3  # kernel sees compressed KV
    assert seen["kv_mask"].shape == (b, nc // 3)  # ...and the pooled mask
    # pooled mask: windows [24..26] contain a valid position -> True;
    # windows [27..29] all padded -> False
    assert bool(seen["kv_mask"][0, 8]) and not bool(seen["kv_mask"][0, 9])
    assert np.allclose(out_f, out_d, atol=1e-6)


def test_context_parallel_excludes_compression(monkeypatch):
    # the compressed KV length no longer matches the sp shard layout, so the
    # context-parallel fused path must not engage when compress_ratio > 1 —
    # even with an active sp mesh (faked here so the gate itself is what is
    # under test, not the mesh lookup)
    import types

    from alphafold2_tpu.parallel import seq_parallel as sp_mod
    from alphafold2_tpu.parallel import sharding as sharding_mod

    calls = {"n": 0}

    def boom(*a, **kw):
        calls["n"] += 1
        raise AssertionError("context-parallel path taken with compressed KV")

    fake_mesh = types.SimpleNamespace(axis_names=(sp_mod.SEQ_AXIS_NAME,))
    monkeypatch.setattr(sp_mod, "sequence_parallel_attention", boom)
    monkeypatch.setattr(sharding_mod, "active_mesh", lambda: fake_mesh)

    x = jax.random.normal(jax.random.key(9), (1, 8, 32))
    ctx = jax.random.normal(jax.random.key(10), (1, 12, 32))
    a = Attention(dim=32, heads=2, dim_head=16, compress_ratio=2,
                  context_parallel="ring", use_flash=False)
    params = a.init(jax.random.key(11), x, context=ctx)
    out = a.apply(params, x, context=ctx)  # compressed: gate skips the path
    assert np.all(np.isfinite(out)) and calls["n"] == 0

    # sanity that the fake-mesh plumbing reaches the path when uncompressed:
    # the same call without compression must enter it (and hit the mock)
    b = Attention(dim=32, heads=2, dim_head=16, context_parallel="ring",
                  use_flash=False)
    plain = Attention(dim=32, heads=2, dim_head=16, use_flash=False)
    params_b = plain.init(jax.random.key(12), x, context=ctx)  # same params
    with np.testing.assert_raises(AssertionError):
        b.apply(params_b, x, context=ctx)
    assert calls["n"] == 1


def test_flash_pads_to_block_multiples(monkeypatch):
    """The stock kernel hard-requires both sequence axes divisible by 128;
    the wrapper must pad (mask-excluding the padding) and slice the output
    — otherwise e.g. compressed-KV lengths silently fall back to dense."""
    import jax.experimental.pallas.ops.tpu.flash_attention as stock

    from alphafold2_tpu.ops import flash as flash_mod

    seen = {}

    def fake_kernel(q, k, v, *, segment_ids=None, sm_scale=1.0, **kw):
        seen["nq"], seen["nk"] = q.shape[2], k.shape[2]
        seen["seg"] = segment_ids
        return jnp.zeros(q.shape, q.dtype)

    monkeypatch.setattr(flash_mod, "flash_available", lambda: True)
    monkeypatch.setattr(stock, "flash_attention", fake_kernel)

    b, h, nq, nk, d = 1, 2, 200, 342, 16
    q = jnp.ones((b, h, nq, d))
    k = jnp.ones((b, h, nk, d))
    v = jnp.ones((b, h, nk, d))
    out = flash_mod.flash_attention(q, k, v)
    assert out.shape == (b, h, nq, d)  # sliced back to the caller's nq
    assert seen["nq"] == 256 and seen["nk"] == 384  # padded to 128 multiples
    qs, ks = seen["seg"].q, seen["seg"].kv
    # padding positions are mask-excluded (segment id 0 vs valid 1)
    assert qs.shape == (b, 256) and ks.shape == (b, 384)
    assert bool(qs[0, nq - 1]) and not bool(qs[0, nq])
    assert bool(ks[0, nk - 1]) and not bool(ks[0, nk])

    # aligned shapes with no masks still skip segment-id construction
    q2 = jnp.ones((b, h, 128, d))
    flash_mod.flash_attention(q2, q2, q2)
    assert seen["seg"] is None


def test_flash_engages_with_one_short_axis(monkeypatch):
    # nq huge / nk sub-block (compressed context): the short axis is padded
    # to one block instead of silently falling back to the dense path
    import jax.experimental.pallas.ops.tpu.flash_attention as stock

    from alphafold2_tpu.ops import flash as flash_mod

    seen = {}

    def fake_kernel(q, k, v, *, segment_ids=None, sm_scale=1.0, **kw):
        seen["nk"] = k.shape[2]
        return jnp.zeros(q.shape, q.dtype)

    monkeypatch.setattr(flash_mod, "flash_available", lambda: True)
    monkeypatch.setattr(stock, "flash_attention", fake_kernel)

    q = jnp.ones((1, 2, 256, 16))
    k = jnp.ones((1, 2, 86, 16))
    out = flash_mod.flash_attention(q, k, k)
    assert out.shape == (1, 2, 256, 16)
    assert seen["nk"] == 128  # padded up to one block

    # both axes sub-block: dense stays preferred
    tiny = jnp.ones((1, 2, 64, 16))
    assert flash_mod.flash_attention(tiny, tiny, tiny) is None
