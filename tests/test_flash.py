"""Flash-attention wrapper gating tests. The fused kernel itself is the
stock JAX Pallas TPU op (compiled only on TPU backends; AF2TPU_TEST_TPU=1
runs these paths on hardware) — what is tested hermetically is the
gating/fallback contract the model relies on."""

import jax
import jax.numpy as jnp
import numpy as np

from alphafold2_tpu.ops.attention import Attention
from alphafold2_tpu.ops.flash import flash_attention, flash_available


def test_unavailable_off_tpu_returns_none():
    assert not flash_available()  # suite runs on the CPU backend
    q = jnp.ones((1, 2, 16, 8))
    assert flash_attention(q, q, q) is None


def test_attention_use_flash_true_falls_back_cleanly():
    # explicit use_flash=True off-TPU: wrapper returns None, dense path runs,
    # numbers identical to use_flash=False
    x = jax.random.normal(jax.random.key(0), (2, 24, 32))
    mask = jnp.ones((2, 24), bool).at[:, 20:].set(False)
    a_flash = Attention(dim=32, heads=2, dim_head=16, use_flash=True)
    a_dense = Attention(dim=32, heads=2, dim_head=16, use_flash=False)
    params = a_dense.init(jax.random.key(1), x, mask=mask)
    out_f = a_flash.apply(params, x, mask=mask)
    out_d = a_dense.apply(params, x, mask=mask)
    assert np.allclose(out_f, out_d, atol=1e-6)


def test_flash_skipped_for_tied_rows_and_dropout(monkeypatch):
    # tied rows and attn dropout are dense-path features; flash gating must
    # not change their outputs
    x = jax.random.normal(jax.random.key(2), (4, 8, 32))  # (B*R, n, d)
    a = Attention(dim=32, heads=2, dim_head=16, use_flash=True)
    b = Attention(dim=32, heads=2, dim_head=16, use_flash=False)
    params = b.init(jax.random.key(3), x, tie_dim=2)
    assert np.allclose(
        a.apply(params, x, tie_dim=2), b.apply(params, x, tie_dim=2), atol=1e-6
    )

    # dropout gate: with attn dropout active (deterministic=False), the flash
    # path must NOT be taken even when the kernel is "available" — attention-
    # weight dropout needs materialized probabilities
    from alphafold2_tpu.ops import flash as flash_mod

    def boom(*a, **kw):  # pragma: no cover - must not be reached
        raise AssertionError("flash path taken despite active attn dropout")

    drop = Attention(dim=32, heads=2, dim_head=16, dropout=0.5, use_flash=None)
    params_d = drop.init(jax.random.key(4), x)  # before the mock: init is deterministic
    monkeypatch.setattr(flash_mod, "flash_available", lambda: True)
    monkeypatch.setattr(flash_mod, "flash_attention", boom)
    out = drop.apply(
        params_d, x, deterministic=False, rngs={"dropout": jax.random.key(5)}
    )
    assert np.all(np.isfinite(out))
    # ...and with deterministic=True the (mocked) flash path IS selected
    with np.testing.assert_raises(AssertionError):
        drop.apply(params_d, x, deterministic=True)
