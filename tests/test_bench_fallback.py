"""bench.py first-light fallback + phase-aware failure records (VERDICT r3 #1).

The flagship bench must (a) be able to measure a smaller config in-process
and hold it as the fallback result, (b) emit that fallback (a real nonzero
number) instead of a value-0.0 record when the flagship attempt dies, and
(c) say WHICH phase a deadline kill happened in — "backend init never
returned" and "compile too slow" demand different operator responses.
"""

import json

import pytest

import bench


@pytest.fixture(autouse=True)
def _reset_bench_globals():
    bench._FIRST_LIGHT["record"] = None
    bench._emitted = False
    bench._PHASE["name"] = "startup"
    yield
    bench._FIRST_LIGHT["record"] = None
    bench._emitted = False
    bench._PHASE["name"] = "startup"


def test_main_with_overrides_measures_without_emitting(capsys):
    rec = bench.main(
        overrides={"crop": 24, "msa_depth": 2, "msa_len": 24, "dim": 16,
                   "depth": 1},
        emit=False,
    )
    assert rec["value"] > 0
    assert "crop=24" in rec["metric"] and "dim=16" in rec["metric"]
    # an override run must never be compared against the flagship baseline
    assert rec["vs_baseline_valid"] is False
    assert capsys.readouterr().out == ""  # emit=False: nothing on stdout
    assert not bench._emitted


def test_emit_failure_prefers_first_light(capsys):
    bench._FIRST_LIGHT["record"] = {
        "metric": "residue-pairs/sec/chip crop=128 ...",
        "value": 123.4, "unit": "pairs/sec",
        "vs_baseline": 1.0, "vs_baseline_valid": False, "mfu": 0.21,
    }
    bench._emit_failure("deadline 1500s exceeded during phase "
                        "'trace_compile': compile exceeded the remaining "
                        "budget")
    out = json.loads(capsys.readouterr().out)
    assert out["value"] == 123.4  # the real measurement, not 0.0
    assert out["fallback"] is True
    assert "trace_compile" in out["flagship_error"]
    assert out["mfu"] == 0.21


def test_emit_failure_without_first_light_reports_phase(capsys):
    bench._PHASE["name"] = "backend_init"
    bench._emit_failure(bench._phase_failure_msg())
    out = json.loads(capsys.readouterr().out)
    assert out["value"] == 0.0
    assert out["phase"] == "backend_init"
    assert "backend init never returned" in out["error"]


@pytest.mark.parametrize("phase,needle", [
    ("backend_init", "backend init never returned"),
    ("first_light:backend_init", "backend init never returned"),
    ("trace_compile", "compile exceeded"),
    ("warmup_run", "too slow"),
    ("timed_run", "too slow"),
    ("startup", "before touching the backend"),
])
def test_phase_failure_messages(phase, needle):
    bench._PHASE["name"] = phase
    msg = bench._phase_failure_msg()
    assert needle in msg and phase in msg


def test_flagship_record_carries_first_light_evidence(monkeypatch):
    """When the flagship succeeds after a first-light measurement, the one
    emitted JSON line records both (the driver stores only that line)."""
    small = {"crop": 24, "msa_depth": 2, "msa_len": 24, "dim": 16, "depth": 1}
    fl = bench.main(overrides=small, emit=False)
    bench._FIRST_LIGHT["record"] = fl
    assert "first_light" not in fl  # override runs never self-attach

    # shrink the module-default "flagship" so the no-overrides path runs
    # at test size on CPU
    monkeypatch.setattr(bench, "CROP", 24)
    monkeypatch.setattr(bench, "MSA_DEPTH", 2)
    monkeypatch.setattr(bench, "MSA_LEN", 24)
    monkeypatch.setattr(bench, "DIM", 16)
    monkeypatch.setattr(bench, "DEPTH", 1)
    rec = bench.main(emit=False)
    assert rec["value"] > 0
    assert rec["first_light"]["value"] == fl["value"]
    assert rec["first_light"]["metric"] == fl["metric"]
