"""Scan-over-layers trunk tests: the scanned trunk must be the same network
as the python-loop trunk (outputs equal under stacked params), compose with
remat, and reject heterogeneous per-layer configs."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from alphafold2_tpu.models import Alphafold2


KW = dict(dim=32, depth=3, heads=2, dim_head=16, max_seq_len=64)


def _inputs():
    k = jax.random.key(0)
    seq = jax.random.randint(jax.random.fold_in(k, 1), (1, 8), 0, 21)
    msa = jax.random.randint(jax.random.fold_in(k, 2), (1, 2, 8), 0, 21)
    mask = jnp.ones((1, 8), bool)
    msa_mask = jnp.ones((1, 2, 8), bool)
    return seq, msa, mask, msa_mask


def _stack_loop_params_into_scan(loop_params, scan_params, depth):
    """Map layer_0..layer_{d-1} subtrees onto the scanned (stacked) tree."""
    lp = loop_params["params"]["trunk"]
    stacked = jax.tree.map(
        lambda *leaves: jnp.stack(leaves),
        *[lp[f"layer_{i}"] for i in range(depth)],
    )
    out = jax.tree.map(lambda x: x, scan_params)  # deep copy of structure
    out["params"]["trunk"]["scan"]["layer"] = stacked
    # everything outside the trunk is shared verbatim
    for k, v in loop_params["params"].items():
        if k != "trunk":
            out["params"][k] = v
    return out


def test_scan_equals_loop_with_stacked_params():
    seq, msa, mask, msa_mask = _inputs()
    loop_model = Alphafold2(scan_layers=False, **KW)
    scan_model = Alphafold2(scan_layers=True, **KW)
    loop_params = loop_model.init(jax.random.key(3), seq, msa, mask=mask,
                                  msa_mask=msa_mask)
    scan_params = scan_model.init(jax.random.key(3), seq, msa, mask=mask,
                                  msa_mask=msa_mask)
    mapped = _stack_loop_params_into_scan(loop_params, scan_params, KW["depth"])
    out_loop = loop_model.apply(loop_params, seq, msa, mask=mask,
                                msa_mask=msa_mask)
    out_scan = scan_model.apply(mapped, seq, msa, mask=mask, msa_mask=msa_mask)
    assert np.allclose(out_loop, out_scan, atol=1e-5), (
        np.abs(np.asarray(out_loop - out_scan)).max()
    )
    # same parameter count
    n_loop = sum(x.size for x in jax.tree.leaves(loop_params))
    n_scan = sum(x.size for x in jax.tree.leaves(scan_params))
    assert n_loop == n_scan


@pytest.mark.slow
def test_scan_with_remat_grads_match():
    seq, msa, mask, msa_mask = _inputs()
    base = Alphafold2(scan_layers=True, remat=False, **KW)
    remat = Alphafold2(scan_layers=True, remat=True, **KW)
    params = base.init(jax.random.key(4), seq, msa, mask=mask, msa_mask=msa_mask)

    def loss(model, p):
        return jnp.sum(
            model.apply(p, seq, msa, mask=mask, msa_mask=msa_mask) ** 2
        )

    g1 = jax.grad(lambda p: loss(base, p))(params)
    g2 = jax.grad(lambda p: loss(remat, p))(params)
    for a, b in zip(jax.tree.leaves(g1), jax.tree.leaves(g2)):
        assert np.allclose(a, b, atol=1e-4)


@pytest.mark.parametrize("remat", [False, True])
def test_scan_dropout_rng_plumbing(remat):
    # the scan-lifted dropout rng path (split_rngs + remat-wrapped layer)
    # must run and actually drop (stochastic across keys)
    seq, msa, mask, msa_mask = _inputs()
    model = Alphafold2(scan_layers=True, remat=remat, attn_dropout=0.3,
                       ff_dropout=0.3, **KW)
    params = model.init(jax.random.key(6), seq, msa, mask=mask,
                        msa_mask=msa_mask)
    outs = [
        model.apply(params, seq, msa, mask=mask, msa_mask=msa_mask,
                    deterministic=False, rngs={"dropout": jax.random.key(s)})
        for s in (0, 1)
    ]
    assert np.all(np.isfinite(outs[0]))
    assert not np.allclose(outs[0], outs[1])  # different keys -> different drops


def test_scan_rejects_heterogeneous_sparse():
    seq, msa, mask, msa_mask = _inputs()
    model = Alphafold2(scan_layers=True, sparse_self_attn=(True, False, True),
                       **KW)
    with pytest.raises(ValueError, match="homogeneous"):
        model.init(jax.random.key(5), seq, msa, mask=mask, msa_mask=msa_mask)
