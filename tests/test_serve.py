"""Serve-engine tests: bucket-ladder selection, padded-vs-unpadded
coordinate parity (padding cannot change valid-region output), batching
parity (co-batched requests cannot change each other), and compile-count
accounting (mixed lengths in one bucket => exactly 1 compile)."""

import numpy as np
import pytest

from alphafold2_tpu.config import (
    Config,
    DataConfig,
    ModelConfig,
    ServeConfig,
)
from alphafold2_tpu.serve import (
    ServeEngine,
    ServeRequest,
    bucket_for,
    geometric_ladder,
    padding_fraction,
    validate_ladder,
)


def _cfg(buckets=(8, 16, 32), max_batch=3, **serve_kw):
    return Config(
        model=ModelConfig(dim=32, depth=1, heads=2, dim_head=16,
                          max_seq_len=3 * max(buckets), bfloat16=False),
        data=DataConfig(msa_depth=2),
        serve=ServeConfig(buckets=buckets, max_batch=max_batch,
                          mds_iters=30, **serve_kw),
    )


@pytest.fixture(scope="module")
def engine():
    return ServeEngine(_cfg())


# ---------------------------------------------------------------- bucketing


def test_bucket_ladder_selection():
    buckets = (64, 96, 128, 192, 256)
    assert bucket_for(1, buckets) == 64
    assert bucket_for(64, buckets) == 64
    assert bucket_for(65, buckets) == 96
    assert bucket_for(256, buckets) == 256
    with pytest.raises(ValueError, match="exceeds the largest bucket"):
        bucket_for(257, buckets)
    with pytest.raises(ValueError, match="positive"):
        bucket_for(0, buckets)


def test_ladder_validation_and_geometry():
    assert validate_ladder([64, 96]) == (64, 96)
    with pytest.raises(ValueError, match="ascending"):
        validate_ladder((96, 64))
    with pytest.raises(ValueError, match="empty"):
        validate_ladder(())
    ladder = geometric_ladder(64, 256, ratio=1.5)
    assert ladder[0] == 64 and ladder[-1] >= 256
    assert all(a < b for a, b in zip(ladder, ladder[1:]))  # strictly ascends
    # every request length in range has a rung
    for n in range(1, 257):
        assert bucket_for(n, ladder) >= n
    # padding waste: exact-fit lengths pad nothing
    assert padding_fraction([64, 96], (64, 96)) == 0.0
    assert padding_fraction([1], (4,)) == 0.75


def test_config_roundtrip_keeps_bucket_tuple():
    cfg = _cfg(buckets=(8, 16))
    back = Config.from_json(cfg.to_json())
    assert back.serve.buckets == (8, 16)
    over = Config().apply_overrides(["serve.buckets=32,64", "serve.max_batch=2"])
    assert over.serve.buckets == (32, 64)
    assert over.serve.max_batch == 2


def test_engine_rejects_oversized_ladder():
    cfg = _cfg(buckets=(8, 64))  # 3*64 > max_seq_len(=96) after _cfg? no:
    cfg.model.max_seq_len = 96  # force the violation: 3*64=192 > 96
    with pytest.raises(ValueError, match="max_seq_len"):
        ServeEngine(cfg)


# ------------------------------------------------------- padding/batch parity


def test_padded_content_cannot_change_valid_region(engine):
    """Adversarial pad-content test at a FIXED executable shape: the same
    request dispatched beside garbage in the padded length region and in the
    dummy batch slots must produce identical valid-region coordinates."""
    req = ServeRequest("ACDEFG", seed=3)  # 6 residues in the 8-bucket
    clean = engine.predict_many([req])[0]

    # hand-build the same dispatch with adversarial padding: garbage tokens
    # in the padded tail of the valid slot and a garbage (masked) dummy slot
    import jax

    from alphafold2_tpu import constants
    from alphafold2_tpu.data.pipeline import featurize_bucketed
    from alphafold2_tpu.predict import encode_sequence

    bucket, batch = 8, engine.max_batch
    item = featurize_bucketed(encode_sequence(req.seq)[0], bucket,
                              engine.msa_depth, seed=req.seed)
    rng = np.random.default_rng(7)
    stacked = {
        "seq": np.stack([item["seq"]] * batch),
        "mask": np.stack([item["mask"]] * batch),
        "msa": np.stack([item["msa"]] * batch),
        "msa_mask": np.stack([item["msa_mask"]] * batch),
    }
    # slot 0 carries the request; its masked tail gets garbage tokens
    stacked["seq"][0, 6:] = rng.integers(0, 20, size=bucket - 6)
    stacked["msa"][0, :, 6:] = rng.integers(0, 20, size=(engine.msa_depth,
                                                         bucket - 6))
    # the other slots are fully-masked garbage (mask all False)
    for b in range(1, batch):
        stacked["seq"][b] = rng.integers(0, 20, size=bucket)
        stacked["msa"][b] = rng.integers(0, 20,
                                         size=(engine.msa_depth, bucket))
        stacked["mask"][b] = False
        stacked["msa_mask"][b] = False

    compiled = engine._get_executable(bucket, batch)
    out = compiled(engine.params, stacked["seq"], stacked["msa"],
                   stacked["mask"], stacked["msa_mask"])
    refined = np.asarray(jax.device_get(out["refined"]))[0, :6]
    np.testing.assert_allclose(refined, clean.atom14, atol=1e-5)


def test_bucket_padding_parity_across_shapes():
    """The SAME request served from two different bucket shapes must agree
    on the valid region: masked MDS weights + effective-N Guttman steps +
    position-keyed init + mask-aware psi make realization shape-blind."""
    e8 = ServeEngine(_cfg(buckets=(8, 16), max_batch=2))
    e16 = ServeEngine(_cfg(buckets=(16,), max_batch=2), params=e8.params)
    r8 = e8.predict_many([ServeRequest("ACDEFGHK", seed=1)])[0]
    r16 = e16.predict_many([ServeRequest("ACDEFGHK", seed=1)])[0]
    assert r8.bucket == 8 and r16.bucket == 16
    np.testing.assert_allclose(r16.atom14, r8.atom14, atol=1e-4)
    np.testing.assert_allclose(r16.weights, r8.weights, atol=1e-5)


@pytest.mark.usefixtures("no_implicit_transfers")
def test_batching_parity(engine):
    """A request's output must not depend on what else rides in the batch
    or which slot it lands in. Runs under jax.transfer_guard("disallow"):
    the whole serve path must transfer explicitly (conftest fixture)."""
    a = ServeRequest("ACDEFG", seed=11)
    solo = engine.predict_many([a])[0]
    batched = engine.predict_many(
        [ServeRequest("MKVLIT", seed=5), a, ServeRequest("AC", seed=9)]
    )[1]
    np.testing.assert_allclose(batched.atom14, solo.atom14, atol=1e-5)
    np.testing.assert_allclose(batched.weights, solo.weights, atol=1e-6)


@pytest.mark.usefixtures("no_implicit_transfers")
def test_results_align_with_requests(engine):
    reqs = ["ACDEFGHKLM", "AC", "ACDEFGHKLMNPQRSTVW"]
    out = engine.predict_many(reqs)
    for seq, r in zip(reqs, out):
        assert r.seq == seq
        assert r.atom14.shape == (len(seq), 14, 3)
        assert r.backbone.shape == (len(seq), 3, 3)
        assert r.weights.shape == (3 * len(seq), 3 * len(seq))
        assert np.all(np.isfinite(r.atom14))
        assert r.latency_s > 0
        assert r.distogram is None  # return_distogram defaults off


def test_serve_trace_strict_and_transfer_clean(
    fresh_engine, strict_promotion, no_implicit_transfers
):
    """Trace + compile + dispatch of a fresh engine under BOTH graph-
    hygiene guards: strict dtype promotion (no implicit bool/int->float
    widening anywhere in the serve graph) and disallowed implicit
    transfers (every host<->device hop in the dispatch path is explicit).
    Fixture order matters: the engine (params, PRNG keys) is built before
    the guards engage."""
    out = fresh_engine.predict_many(["ACDEFG", "MK"])
    assert out[0].atom14.shape == (6, 14, 3)
    assert out[1].atom14.shape == (2, 14, 3)
    assert np.all(np.isfinite(out[0].atom14))
    assert fresh_engine.stats()["serve.compiles"] == 1


@pytest.fixture
def fresh_engine():
    # function-scoped: nothing compiled yet, so the guarded test above
    # exercises trace+compile, not just a cache-hit dispatch
    return ServeEngine(_cfg(buckets=(8,), max_batch=2))


# ------------------------------------------------------- compile accounting


def test_mixed_lengths_one_bucket_compile_exactly_once():
    eng = ServeEngine(_cfg())
    # 5 requests of 4 distinct lengths, all <= 8 -> one bucket
    eng.predict_many(["ACDE", "ACDEF", "ACDEFG", "ACDEFGHK", "AC"])
    s = eng.stats()
    assert s["serve.compiles"] == 1, s
    assert s["serve.traces"] == 1, s  # python-side proof: one trace, ever
    assert s["serve.requests"] == 5
    assert s["serve.batches"] == 2  # 5 requests / max_batch 3
    assert s["serve.cache_hits"] == 1  # second dispatch reused the first's

    # a length crossing into the next rung compiles exactly one more
    eng.predict_many(["ACDEFGHKLMNP"])  # 12 residues -> bucket 16
    s = eng.stats()
    assert s["serve.compiles"] == 2, s
    assert s["serve.traces"] == 2, s

    # and everything after that is cache hits
    eng.predict_many(["ACD", "ACDEFGHKLM", "ACDEFGHK"])
    assert eng.stats()["serve.compiles"] == 2


def test_warmup_precompiles_ladder():
    eng = ServeEngine(_cfg(buckets=(8, 16), max_batch=2))
    snap = eng.warmup()
    assert snap["serve.compiles"] == 2
    eng.predict_many(["ACDE", "ACDEFGHKLM"])
    s = eng.stats()
    assert s["serve.compiles"] == 2  # traffic compiled nothing new
    assert s["serve.cache_hits"] == 2


# ----------------------------------------------------------- observability


def test_engine_histograms_and_compile_records():
    """The engine streams latency/queue-wait/dispatch/occupancy/pad-ratio
    distributions and records per-(bucket,batch) compile durations."""
    eng = ServeEngine(_cfg())
    res = eng.predict_many(["ACDE", "ACDEF", "ACDEFG", "ACDEFGHKLMNP"])
    h = eng.histograms
    assert h["latency_s"].count == 4  # one observation per request
    # queue wait is per REQUEST (each request can carry its own arrival);
    # dispatch/occupancy stay per dispatch: 3 reqs in the 8-bucket (one
    # full batch) + 1 in 16
    assert h["queue_wait_s"].count == 4
    assert h["dispatch_s"].count == 2
    assert h["batch_occupancy"].count == 2
    assert h["pad_ratio"].count == 4
    assert 0 < h["batch_occupancy"].snapshot()["max"] <= 1.0
    # latency decomposes: queue wait + dispatch, and both ride the result
    for r in res:
        assert r.latency_s > 0
        assert abs(r.latency_s - (r.queue_wait_s + r.dispatch_s)) < 1e-9
    shapes = {(c["bucket"], c["batch"]) for c in eng.compile_records}
    assert shapes == {(8, 3), (16, 3)}
    assert all(c["seconds"] > 0 for c in eng.compile_records)
    assert len(eng.compile_records) == eng.stats()["serve.compiles"]
    # flops accounting (observe.flops) rides on every build and dispatch
    assert all(c.get("flops", 0) > 0 for c in eng.compile_records)
    assert eng.executed_flops > 0


def test_engine_traces_request_lifecycle(tmp_path):
    """With a tracer attached, one dispatch emits the full span lifecycle
    (featurize -> get_executable/compile -> dispatch -> device_get ->
    unpad) in valid Chrome trace-event form."""
    from alphafold2_tpu.observe import Tracer
    from alphafold2_tpu.observe.tracing import load_trace_events

    path = str(tmp_path / "serve_trace.json")
    tracer = Tracer(path)
    eng = ServeEngine(_cfg(buckets=(8,), max_batch=2), tracer=tracer)
    eng.predict_many(["ACDEFG", "MKVLIT", "AC"])
    tracer.close()

    events = load_trace_events(path)
    spans = [e for e in events if e["ph"] == "X"]
    names = [e["name"] for e in spans]
    for expected in ("serve.batch", "serve.featurize",
                     "serve.get_executable", "serve.compile",
                     "serve.dispatch", "serve.device_get", "serve.unpad"):
        assert expected in names, (expected, sorted(set(names)))
    assert names.count("serve.batch") == 2  # 3 requests / max_batch 2
    assert names.count("serve.compile") == 1  # second dispatch cache-hits
    # cache verdict is attached to the get_executable spans
    verdicts = [
        e["args"]["compiled_now"] for e in spans
        if e["name"] == "serve.get_executable"
    ]
    assert verdicts == [True, False]
    # spans nest inside their serve.batch parent on the same thread
    batch0 = next(e for e in spans if e["name"] == "serve.batch")
    feat0 = next(e for e in spans if e["name"] == "serve.featurize")
    assert batch0["ts"] <= feat0["ts"]
    assert feat0["ts"] + feat0["dur"] <= batch0["ts"] + batch0["dur"] + 1


# ------------------------------------------------------------------- bench


def test_bench_serve_emits_valid_record(monkeypatch):
    """The acceptance contract: a nonzero residues/sec record, no error
    field, from real end-to-end timings (tiny config via env knobs)."""
    monkeypatch.setenv("AF2TPU_SERVE_BUCKETS", "8,16")
    monkeypatch.setenv("AF2TPU_SERVE_MAX_BATCH", "2")
    monkeypatch.setenv("AF2TPU_SERVE_REQUESTS", "4")
    monkeypatch.setenv("AF2TPU_SERVE_DIM", "32")
    monkeypatch.setenv("AF2TPU_SERVE_DEPTH", "1")
    monkeypatch.setenv("AF2TPU_SERVE_HEADS", "2")
    monkeypatch.setenv("AF2TPU_SERVE_DIM_HEAD", "16")
    monkeypatch.setenv("AF2TPU_SERVE_MSA_DEPTH", "2")
    monkeypatch.setenv("AF2TPU_SERVE_MDS_ITERS", "8")
    import bench

    record = bench.bench_serve(emit=False)
    assert "error" not in record
    assert record["unit"] == "residues/sec"
    assert record["value"] > 0
    assert record["p50_ms"] > 0 and record["p95_ms"] >= record["p50_ms"]
    assert record["compiles"] == 2  # one per ladder rung (warmup)
    # env-overridden config: must never claim a baseline comparison
    assert record["vs_baseline_valid"] is False


def test_bench_mode_parsing():
    import bench

    assert bench.bench_mode([]) == "train"
    assert bench.bench_mode(["--mode", "serve"]) == "serve"
    assert bench.bench_mode(["--mode=serve"]) == "serve"
