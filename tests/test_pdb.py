"""PDB I/O tests: codec round-trip, chain cleaning, scaffold coordinate
replacement (the custom2pdb path, reference utils.py:131-158), and the
scaffold-free backbone export."""

import numpy as np
import pytest

from alphafold2_tpu.utils import pdb


def _fixture_structure():
    # two chains; chain B has a HETATM water
    bb = np.asarray(
        [
            [[0.0, 0, 0], [1.46, 0, 0], [2.4, 1.1, 0]],
            [[3.8, 1.2, 0.4], [5.2, 1.3, 0.5], [6.1, 2.4, 0.6]],
        ],
        np.float32,
    )
    s = pdb.backbone_to_pdb("AG", bb, chain="A")
    w = pdb.PDBStructure(
        serial=np.asarray([99], np.int32),
        name=np.asarray(["O"], "<U4"),
        resname=np.asarray(["HOH"], "<U3"),
        chain=np.asarray(["B"], "<U1"),
        resseq=np.asarray([1], np.int32),
        coords=np.asarray([[9.0, 9.0, 9.0]], np.float32),
        element=np.asarray(["O"], "<U2"),
        hetero=np.asarray([True]),
    )
    return pdb.PDBStructure(
        *(
            np.concatenate([getattr(s, f.name), getattr(w, f.name)])
            for f in s.__dataclass_fields__.values()
        )
    )


def test_roundtrip_parse_write():
    s = _fixture_structure()
    text = pdb.to_pdb_string(s)
    p = pdb.parse_pdb(text)
    assert len(p) == len(s)
    assert list(p.name) == list(s.name)
    assert list(p.resname) == list(s.resname)
    assert np.allclose(p.coords, s.coords, atol=1e-3)  # 3-decimal PDB cols
    assert p.hetero[-1] and not p.hetero[0]


def test_ca_trace_and_chains():
    s = _fixture_structure()
    assert s.chains() == ["A", "B"]
    seq, ca = s.ca_trace()
    assert seq == "AG"
    assert ca.shape == (2, 3)
    assert np.allclose(ca[0], [1.46, 0, 0], atol=1e-3)


def test_clean_pdb_selects_chain(tmp_path):
    s = _fixture_structure()
    src = str(tmp_path / "in.pdb")
    pdb.save_pdb(s, src)
    out = pdb.clean_pdb(src, route=str(tmp_path / "out.pdb"), chain_id="A")
    cleaned = pdb.load_pdb(out)
    assert cleaned.chains() == ["A"]
    assert not cleaned.hetero.any()
    # chain_num path (0-based file order) picks the same chain
    out2 = pdb.clean_pdb(src, route=str(tmp_path / "out2.pdb"), chain_num=0)
    assert pdb.load_pdb(out2).chains() == ["A"]


def test_custom2pdb_with_local_scaffold(tmp_path):
    s = _fixture_structure()
    scaffold = str(tmp_path / "scaffold.pdb")
    pdb.clean_pdb(pdb.save_pdb(s, scaffold))
    n = len(pdb.load_pdb(scaffold))
    new_coords = np.arange(n * 3, dtype=np.float32).reshape(n, 3)
    _, route = pdb.custom2pdb(
        new_coords, "x#1ABC_0_A", str(tmp_path / "out.pdb"),
        scaffold_path=scaffold,
    )
    got = pdb.load_pdb(route)
    assert np.allclose(got.coords, new_coords, atol=1e-3)
    # (3, N) transposed input accepted like the reference
    _, route2 = pdb.custom2pdb(
        new_coords.T, "x#1ABC_0_A", str(tmp_path / "out2.pdb"),
        scaffold_path=scaffold,
    )
    assert np.allclose(pdb.load_pdb(route2).coords, new_coords, atol=1e-3)


def test_download_gated():
    with pytest.raises(RuntimeError, match="download"):
        pdb.download_pdb("1ABC", "/tmp/should_not_exist.pdb", timeout=0.2)


def test_backbone_to_pdb_ca_only():
    ca = np.random.default_rng(0).normal(size=(5, 3)).astype(np.float32)
    s = pdb.backbone_to_pdb([0, 1, 2, 3, 4], ca)
    assert len(s) == 5
    assert set(s.name) == {"CA"}
    assert pdb.parse_pdb(pdb.to_pdb_string(s)).resseq[-1] == 5
