"""PLM embedding-provider tests: the hermetic hash provider, the
precomputed-archive provider, the dataset adapter, and a full train step on
the embedds path (which crashes in the reference — SURVEY.md S2.5)."""

import jax
import numpy as np
import pytest

from alphafold2_tpu.config import Config, DataConfig, ModelConfig, TrainConfig
from alphafold2_tpu.data.pipeline import SyntheticDataset
from alphafold2_tpu.data.plm import (
    HashProjectionProvider,
    PrecomputedProvider,
    make_provider,
    wrap_with_embeddings,
)
from alphafold2_tpu.train.loop import (
    build_model,
    device_put_batch,
    init_state,
    make_train_step,
)


def _data_cfg():
    return DataConfig(crop_len=16, msa_depth=2, msa_len=16, batch_size=2,
                      min_len_filter=8)


def test_hash_provider_shapes_and_determinism():
    p1 = HashProjectionProvider(dim=64, seed=0)
    p2 = HashProjectionProvider(dim=64, seed=0)
    seq = np.random.default_rng(0).integers(0, 21, size=(2, 10))
    e1, e2 = p1(seq), p2(seq)
    assert e1.shape == (2, 10, 64)
    assert np.array_equal(e1, e2)
    # position matters: same AA at different positions embeds differently
    seq_same = np.zeros((1, 10), np.int64)
    e = p1(seq_same)
    assert not np.allclose(e[0, 0], e[0, 1])


def test_precomputed_provider_roundtrip(tmp_path):
    from alphafold2_tpu import constants

    seq = np.asarray([[0, 1, 2, 3]])
    key = "".join(constants.AA_ALPHABET[t] for t in seq[0])
    want = np.random.default_rng(1).normal(size=(4, 8)).astype(np.float32)
    path = str(tmp_path / "emb.npz")
    np.savez(path, **{key: want})
    got = PrecomputedProvider(path)(seq)
    assert np.allclose(got[0], want)
    with pytest.raises(KeyError):
        PrecomputedProvider(path)(np.asarray([[4, 4, 4, 4]]))


def test_wrap_with_embeddings_drops_msa():
    cfg = _data_cfg()
    provider = make_provider("hash", dim=32)
    stream = wrap_with_embeddings(iter(SyntheticDataset(cfg, seed=0)), provider)
    batch = next(stream)
    assert "msa" not in batch and "msa_mask" not in batch
    assert batch["embedds"].shape == (2, 16, 32)


def test_train_step_on_embedds_path():
    cfg = Config(
        model=ModelConfig(dim=32, depth=1, heads=2, dim_head=16, max_seq_len=64,
                          bfloat16=False),
        data=_data_cfg(),
        train=TrainConfig(gradient_accumulate_every=1, warmup_steps=2),
    )
    provider = make_provider("hash", dim=1280)  # model default num_embedds
    stream = wrap_with_embeddings(iter(SyntheticDataset(cfg.data, seed=0)),
                                  provider)
    batch = next(stream)
    model = build_model(cfg)
    state = init_state(cfg, model, batch)
    step = make_train_step(model)
    state, metrics = step(state, device_put_batch(batch), jax.random.key(0))
    assert np.isfinite(float(metrics["loss"]))
    assert bool(metrics["grads_ok"])
