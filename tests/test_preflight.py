"""The shared compile-mode preflight (alphafold2_tpu.preflight) and bench's
cold-cache deadline budgeting around it.

The real probe launches jax subprocesses against the axon relay; here the
probe is monkeypatched — what's under test is the decision logic: when to
skip, when to report both modes dead, and when to flip to client-side
compile and re-exec with the remaining budget.
"""

import os

import pytest

from alphafold2_tpu import preflight


@pytest.fixture(autouse=True)
def _clean_env(monkeypatch):
    for var in (
        "AF2TPU_PLATFORM", "JAX_PLATFORMS", "AF2TPU_NO_PREFLIGHT",
        "PALLAS_AXON_REMOTE_COMPILE", "AF2TPU_PREFLIGHT_CLIENT_OK",
        "AF2TPU_BENCH_DEADLINE",
    ):
        monkeypatch.delenv(var, raising=False)


def test_skipped_on_cpu_platform(monkeypatch):
    monkeypatch.setenv("AF2TPU_PLATFORM", "cpu")
    monkeypatch.setenv("PALLAS_AXON_REMOTE_COMPILE", "1")
    assert preflight.preflight_compile_mode() == "skipped"


def test_skipped_when_already_client_mode(monkeypatch):
    monkeypatch.setenv("PALLAS_AXON_REMOTE_COMPILE", "0")
    assert preflight.preflight_compile_mode() == "skipped"


def test_remote_ok(monkeypatch):
    monkeypatch.setenv("PALLAS_AXON_REMOTE_COMPILE", "1")
    monkeypatch.setattr(preflight, "_probe_ok", lambda *a, **k: True)
    assert preflight.preflight_compile_mode() == "remote_ok"


def test_both_dead(monkeypatch):
    monkeypatch.setenv("PALLAS_AXON_REMOTE_COMPILE", "1")
    monkeypatch.setattr(preflight, "_probe_ok", lambda *a, **k: False)
    assert preflight.preflight_compile_mode() == "both_dead"


def test_reexec_into_client_mode(monkeypatch):
    # remote probe fails, client probe succeeds -> env flipped, remaining
    # budget written into the caller's deadline var, execv with sys.argv
    monkeypatch.setenv("PALLAS_AXON_REMOTE_COMPILE", "1")
    calls = []

    def fake_probe(extra_env=None, timeout=240):
        return bool(extra_env)  # plain probe False, client-mode probe True

    execs = []
    monkeypatch.setattr(preflight, "_probe_ok", fake_probe)
    monkeypatch.setattr(preflight.os, "execv", lambda *a: execs.append(a))
    out = preflight.preflight_compile_mode(
        remaining_fn=lambda: 123.7, deadline_env_var="AF2TPU_BENCH_DEADLINE"
    )
    assert execs, "expected re-exec"
    assert os.environ["PALLAS_AXON_REMOTE_COMPILE"] == "0"
    assert os.environ["AF2TPU_PREFLIGHT_CLIENT_OK"] == "1"
    assert os.environ["AF2TPU_BENCH_DEADLINE"] == "123"
    del calls, out


def test_bench_cold_cache_extension(monkeypatch, tmp_path):
    import bench

    cache = tmp_path / "xla_cache"
    cache.mkdir()
    monkeypatch.setenv("AF2TPU_COMPILE_CACHE", str(cache))
    # healthy probe + empty cache -> extension
    assert bench._cold_cache_deadline_extension("remote_ok") > 0
    # a re-exec'd client-mode process knows via the env marker
    monkeypatch.setenv("AF2TPU_PREFLIGHT_CLIENT_OK", "1")
    assert bench._cold_cache_deadline_extension("skipped") > 0
    monkeypatch.delenv("AF2TPU_PREFLIGHT_CLIENT_OK")
    # no liveness evidence -> no extension (the deadline still guards hangs)
    assert bench._cold_cache_deadline_extension("skipped") == 0
    assert bench._cold_cache_deadline_extension("both_dead") == 0
    # warm cache -> no extension
    (cache / "serialized_exe.bin").write_bytes(b"x")
    assert bench._cold_cache_deadline_extension("remote_ok") == 0
