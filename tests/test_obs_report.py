"""obs_report CLI + bench serve-mode observability acceptance: the serve
bench emits p50/p95/p99 from the streaming Histogram plus per-stage span
timings; the trace file is valid Chrome trace-event JSON that
scripts/obs_report.py summarizes with exit code 0."""

import importlib
import json
import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture()
def obs_report(monkeypatch):
    monkeypatch.syspath_prepend(os.path.join(REPO, "scripts"))
    sys.modules.pop("obs_report", None)
    yield importlib.import_module("obs_report")
    sys.modules.pop("obs_report", None)


def _serve_env(monkeypatch, tmp_path):
    for k, v in {
        "AF2TPU_SERVE_BUCKETS": "8,16", "AF2TPU_SERVE_MAX_BATCH": "2",
        "AF2TPU_SERVE_REQUESTS": "4", "AF2TPU_SERVE_DIM": "32",
        "AF2TPU_SERVE_DEPTH": "1", "AF2TPU_SERVE_HEADS": "2",
        "AF2TPU_SERVE_DIM_HEAD": "16", "AF2TPU_SERVE_MSA_DEPTH": "2",
        "AF2TPU_SERVE_MDS_ITERS": "8",
        "AF2TPU_TRACE_EVENTS": str(tmp_path / "trace.json"),
        "AF2TPU_METRICS_DIR": str(tmp_path),
    }.items():
        monkeypatch.setenv(k, v)


@pytest.fixture(scope="module")
def serve_record(tmp_path_factory):
    """One tiny serve bench run shared by the assertions below (module
    scope: the run costs a couple of compiles)."""
    tmp_path = tmp_path_factory.mktemp("obs")
    mp = pytest.MonkeyPatch()
    _serve_env(mp, tmp_path)
    import bench

    try:
        record = bench.bench_serve(emit=False)
    finally:
        mp.undo()
    return record, tmp_path


def test_serve_record_has_histogram_percentiles(serve_record):
    record, _ = serve_record
    assert "error" not in record
    # p50/p95/p99 from the streaming Histogram
    assert record["p50_ms"] > 0
    assert record["p50_ms"] <= record["p95_ms"] <= record["p99_ms"]
    hists = record["histograms"]
    for name in ("latency_ms", "queue_wait_ms", "dispatch_ms",
                 "batch_occupancy", "pad_ratio"):
        assert name in hists, name
    assert hists["latency_ms"]["count"] == 4  # one sample per request
    assert round(hists["latency_ms"]["p50"], 1) == record["p50_ms"]
    assert 0 < hists["batch_occupancy"]["max"] <= 1.0
    assert 0 <= hists["pad_ratio"]["max"] < 1.0
    # compile durations keyed by executable shape
    shapes = {(c["bucket"], c["batch"]) for c in record["compile_records"]}
    assert shapes == {(8, 2), (16, 2)}
    assert all(c["seconds"] > 0 for c in record["compile_records"])


def test_serve_record_has_per_stage_spans(serve_record):
    record, _ = serve_record
    spans = record["spans"]
    for name in ("bench.serve:backend_init", "bench.serve:trace_compile",
                 "bench.serve:timed_run", "serve.featurize",
                 "serve.dispatch", "serve.device_get", "serve.unpad",
                 "serve.compile"):
        assert name in spans, (name, sorted(spans))
        assert spans[name]["count"] >= 1
        assert spans[name]["total_s"] >= 0.0
    assert spans["serve.compile"]["count"] == record["compiles"]


def test_serve_trace_file_is_valid_chrome_format(serve_record):
    from alphafold2_tpu.observe.tracing import load_trace_events

    _, tmp_path = serve_record
    path = tmp_path / "trace.json"
    assert path.exists()
    events = load_trace_events(str(path))
    assert events
    for e in events:
        assert e["ph"] in ("X", "i", "C")
        assert isinstance(e["name"], str)
        assert isinstance(e["ts"], (int, float))
        if e["ph"] == "X":
            assert e["dur"] >= 0
    # the request lifecycle is all present
    names = {e["name"] for e in events if e["ph"] == "X"}
    assert {"serve.featurize", "serve.dispatch", "serve.device_get",
            "serve.unpad", "serve.compile"} <= names


def test_obs_report_summarizes_serve_artifacts(
    serve_record, obs_report, capsys
):
    _, tmp_path = serve_record
    rc = obs_report.main(
        [str(tmp_path / "trace.json"), str(tmp_path / "metrics.jsonl")]
    )
    out = capsys.readouterr().out
    assert rc == 0
    assert "serve.dispatch" in out
    assert "p95" in out
    assert "compile/cache accounting" in out
    assert "executable builds: 2" in out


def test_obs_report_exit_codes(obs_report, tmp_path, capsys):
    assert obs_report.main([]) == 1  # no inputs: usage error
    bad = tmp_path / "nope.json"
    assert obs_report.main([str(bad)]) == 2  # unreadable input
    capsys.readouterr()


def test_obs_report_reads_standalone_metrics(obs_report, tmp_path, capsys):
    from alphafold2_tpu.observe import MetricsLogger

    logger = MetricsLogger(str(tmp_path), enabled=True, echo=False)
    logger.log(0, {"serve.compiles": 3, "serve.cache_hits": 9,
                   "hbm_peak_bytes": 2**30})
    assert obs_report.main([str(tmp_path / "metrics.jsonl")]) == 0
    out = capsys.readouterr().out
    assert "hit rate 75.0%" in out
    assert "HBM peak: 1.000 GiB" in out


def test_obs_report_renders_hlo_contracts(obs_report, capsys):
    """The committed hlo_contracts.json classifies as its own artifact
    kind and renders the census + budget verdicts (the human view of the
    static comm/memory contract the hlo_audit gate diffs)."""
    path = os.path.join(REPO, "hlo_contracts.json")
    assert obs_report.classify(path) == "hlo-contracts"
    assert obs_report.main([path]) == 0
    out = capsys.readouterr().out
    assert "hlo contracts" in out
    assert "serve_fwd_long" in out and "8-way partitioned" in out
    assert "all-gather" in out and "bytes/FLOP" in out
    assert "budget pass" in out
