"""Child process for the real 2-process multi-host test
(tests/test_multihost.py). Each process owns 4 virtual CPU devices; the
pair forms one 8-device (4dp x 2sp) pod. Prints the step loss for the
parent to compare across ranks."""

import os
import sys

os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
os.environ["PALLAS_AXON_POOL_IPS"] = ""

import jax

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    pid = int(sys.argv[1])
    port = sys.argv[2]

    from alphafold2_tpu.parallel.distributed import (
        global_batch,
        initialize,
        pod_mesh,
    )

    ok = initialize(
        coordinator_address=f"127.0.0.1:{port}", num_processes=2, process_id=pid
    )
    assert ok, "distributed init did not run"
    assert jax.process_count() == 2, jax.process_count()
    assert jax.device_count() == 8, jax.device_count()
    assert jax.local_device_count() == 4

    from alphafold2_tpu.config import Config, DataConfig, MeshConfig, ModelConfig, TrainConfig
    from alphafold2_tpu.data.pipeline import SyntheticDataset
    from alphafold2_tpu.train.loop import build_model, init_state, make_train_step

    cfg = Config(
        model=ModelConfig(dim=32, depth=1, heads=2, dim_head=16,
                          max_seq_len=32, bfloat16=False),
        mesh=MeshConfig(data_parallel=4, seq_parallel=2),
        data=DataConfig(crop_len=8, msa_depth=2, msa_len=8, batch_size=2,
                        min_len_filter=8),  # LOCAL batch; global = 4
        train=TrainConfig(gradient_accumulate_every=1, warmup_steps=2,
                          seed=0),
    )
    # each host feeds a DIFFERENT slice of the global batch
    local_batch = next(iter(SyntheticDataset(cfg.data, seed=100 + pid)))

    mesh = pod_mesh(cfg.mesh.data_parallel, cfg.mesh.seq_parallel)
    model = build_model(cfg)
    state = init_state(cfg, model, local_batch)  # same seed -> same params
    step = make_train_step(model, mesh)
    gb = global_batch(local_batch, mesh)
    state, metrics = step(state, gb, jax.random.key(7))
    print(f"RANK {pid} LOSS {float(metrics['loss']):.6f} "
          f"GNORM {float(metrics['grad_norm']):.6f}", flush=True)


if __name__ == "__main__":
    main()
