"""Test harness: run everything on a virtual 8-device CPU mesh.

Must set env before jax's backend initializes — this conftest is imported by
pytest before any test module. Multi-device sharding tests rely on the 8
virtual CPU devices (the reference has no distributed tests at all; this is
the fake-backend mechanism SURVEY.md S4 calls for). Set AF2TPU_TEST_TPU=1 to
run the suite on real accelerators instead.
"""

import os

if not os.environ.get("AF2TPU_TEST_TPU"):
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()

    # Site hooks (e.g. a PJRT plugin registered via sitecustomize) may set
    # jax.config.jax_platforms programmatically at interpreter start, which
    # takes precedence over the env var and would point every test at the
    # accelerator tunnel. Force the config back to CPU before any backend
    # initializes.
    import jax

    jax.config.update("jax_platforms", "cpu")

import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import pytest  # noqa: E402


@pytest.fixture
def strict_promotion():
    """Opt-in graph-hygiene fixture: every trace inside the test runs under
    strict dtype promotion, so an implicit bool/int-into-float promotion
    raises instead of silently widening — the runtime twin of the jaxpr
    auditor's AF2A105 rule (alphafold2_tpu/analysis/jaxpr_audit.py).

    List setup fixtures BEFORE this one in the test signature: fixtures
    instantiate in signature order, so earlier setup stays outside the
    strict context.
    """
    import jax

    with jax.numpy_dtype_promotion("strict"):
        yield


@pytest.fixture
def no_implicit_transfers():
    """Opt-in graph-hygiene fixture: any implicit host<->device transfer
    inside the test raises (jax.transfer_guard("disallow")). Explicit
    jax.device_put / jax.device_get remain allowed — which is the point:
    the serve/train hot paths must only ever transfer explicitly.

    Setup that builds params or PRNG keys (jax.random.key transfers its
    seed scalar) belongs in a fixture listed BEFORE this one.
    """
    import jax

    with jax.transfer_guard("disallow"):
        yield
