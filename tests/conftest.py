"""Test harness: run everything on a virtual 8-device CPU mesh.

Must set env before jax's backend initializes — this conftest is imported by
pytest before any test module. Multi-device sharding tests rely on the 8
virtual CPU devices (the reference has no distributed tests at all; this is
the fake-backend mechanism SURVEY.md S4 calls for). Set AF2TPU_TEST_TPU=1 to
run the suite on real accelerators instead.
"""

import os

if not os.environ.get("AF2TPU_TEST_TPU"):
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()

    # Site hooks (e.g. a PJRT plugin registered via sitecustomize) may set
    # jax.config.jax_platforms programmatically at interpreter start, which
    # takes precedence over the env var and would point every test at the
    # accelerator tunnel. Force the config back to CPU before any backend
    # initializes.
    import jax

    jax.config.update("jax_platforms", "cpu")

import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
