"""Test harness: run everything on a virtual 8-device CPU mesh.

Must set env before jax's backend initializes — this conftest is imported by
pytest before any test module. Multi-device sharding tests rely on the 8
virtual CPU devices (the reference has no distributed tests at all; this is
the fake-backend mechanism SURVEY.md S4 calls for). Set AF2TPU_TEST_TPU=1 to
run the suite on real accelerators instead.
"""

import os

if not os.environ.get("AF2TPU_TEST_TPU"):
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()

    # Site hooks (e.g. a PJRT plugin registered via sitecustomize) may set
    # jax.config.jax_platforms programmatically at interpreter start, which
    # takes precedence over the env var and would point every test at the
    # accelerator tunnel. Force the config back to CPU before any backend
    # initializes.
    import jax

    jax.config.update("jax_platforms", "cpu")

import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import pytest  # noqa: E402


@pytest.fixture
def strict_promotion():
    """Opt-in graph-hygiene fixture: every trace inside the test runs under
    strict dtype promotion, so an implicit bool/int-into-float promotion
    raises instead of silently widening — the runtime twin of the jaxpr
    auditor's AF2A105 rule (alphafold2_tpu/analysis/jaxpr_audit.py).

    List setup fixtures BEFORE this one in the test signature: fixtures
    instantiate in signature order, so earlier setup stays outside the
    strict context.
    """
    import jax

    with jax.numpy_dtype_promotion("strict"):
        yield


@pytest.fixture
def no_implicit_transfers():
    """Opt-in graph-hygiene fixture: any implicit host<->device transfer
    inside the test raises (jax.transfer_guard("disallow")). Explicit
    jax.device_put / jax.device_get remain allowed — which is the point:
    the serve/train hot paths must only ever transfer explicitly.

    Setup that builds params or PRNG keys (jax.random.key transfers its
    seed scalar) belongs in a fixture listed BEFORE this one.
    """
    import jax

    with jax.transfer_guard("disallow"):
        yield


class LockWitness:
    """Test-only instrumented-lock recorder for validating the static
    lock-order graph (alphafold2_tpu/analysis/concurrency.py) against
    runtime reality.

    ``wrap(obj, attr, label)`` replaces a ``threading`` lock attribute
    with a transparent proxy; every acquisition made while another
    wrapped lock is held on the same thread records the observed edge
    ``(held_label, acquired_label)``. Threaded slow-tier tests then
    assert every observed edge appears in the static graph — the model
    validates against reality, and a runtime acquisition the auditor
    cannot see statically fails loudly instead of silently diverging.
    """

    def __init__(self):
        import threading

        self._tls = threading.local()
        self._rec_lock = threading.Lock()
        self.edges = set()  # {(held_label, acquired_label)}

    def _held(self):
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        return stack

    class _Proxy:
        def __init__(self, witness, inner, label):
            self._w = witness
            self._inner = inner
            self._label = label

        def acquire(self, *a, **k):
            got = self._inner.acquire(*a, **k)
            if got is not False:
                held = self._w._held()
                if held:
                    with self._w._rec_lock:
                        self._w.edges.add((held[-1], self._label))
                held.append(self._label)
            return got

        def release(self):
            held = self._w._held()
            if self._label in held:
                held.remove(self._label)
            return self._inner.release()

        def __enter__(self):
            self.acquire()
            return self

        def __exit__(self, *exc):
            self.release()
            return False

        def __getattr__(self, name):
            # Condition.wait/notify, Semaphore internals, etc. pass through;
            # wait() releases and re-acquires the underlying lock itself, so
            # the held stack is intentionally left alone across it
            return getattr(self._inner, name)

    def wrap(self, obj, attr: str, label: str):
        setattr(obj, attr, self._Proxy(self, getattr(obj, attr), label))
        return obj

    def wrap_class(self, cls, attr: str, label: str):
        """Monkeypatch ``cls.__init__`` so every future instance gets its
        ``attr`` lock wrapped. Returns an undo callable."""
        orig = cls.__init__

        def patched(inner_self, *a, **k):
            orig(inner_self, *a, **k)
            self.wrap(inner_self, attr, label)

        cls.__init__ = patched
        return lambda: setattr(cls, "__init__", orig)


@pytest.fixture
def lock_witness():
    """Opt-in concurrency fixture: a fresh LockWitness per test. Wrap the
    locks under test, run the threaded scenario, then assert
    ``witness.edges`` is a subpath of the static lock graph (see
    tests/test_concurrency_audit.py::test_runtime_order_matches_static)."""
    return LockWitness()
