"""Oracle-diff + dispatch tests for the fused Pallas trunk kernels.

The fused dense (axial) and tied-row MSA attention kernels
(ops/pallas/axial.py, ops/pallas/tied_row.py) run here in interpret mode
on the CPU suite — exact, slow — and are diffed against the dense jnp
formulations they replace, values AND grads, across masked / padded /
odd-length shapes (the acceptance bound is 1e-4; measured ~1e-6). The
compiled-mode Mosaic lowering of the same kernels is certified separately
by analysis/lowering.py (test_pallas_lowering.py).

The KernelPolicy switchboard (ops/kernels.py) is pinned too: parse/describe
round-trips, env + context precedence, and the actual dispatch sites —
Attention.__call__'s tied path, the grid-axial hook, SparseAttention's
backend choice — must route where the policy says and nowhere else.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from alphafold2_tpu.ops.kernels import (
    KernelPolicy,
    parse_policy,
    resolve_axial,
    resolve_block_sparse,
    resolve_tied_row,
    use_kernel_policy,
)
from alphafold2_tpu.ops.pallas.axial import fused_attention
from alphafold2_tpu.ops.pallas.tied_row import tied_row_attention

ATOL = 1e-4  # the acceptance bound; measured errors sit near 1e-6


# ------------------------------------------------------------ dense oracles


def dense_attention(q, k, v, q_mask=None, kv_mask=None, scale=1.0):
    dots = jnp.einsum("bhid,bhjd->bhij", q, k).astype(jnp.float32) * scale
    if kv_mask is not None:
        dots = jnp.where(kv_mask[:, None, None, :], dots, -1e30)
    p = jax.nn.softmax(dots, axis=-1)
    out = jnp.einsum("bhij,bhjd->bhid", p, v.astype(jnp.float32))
    out = out.astype(q.dtype)
    if q_mask is not None:  # the kernels' flash convention
        out = jnp.where(q_mask[:, None, :, None], out, 0)
    return out


def dense_tied(q, k, v, qm, km, tie_scale, scale):
    """The dense tied contraction of ops/attention.py (inputs pre-zeroed,
    shared masks, voting-row tie scale)."""
    dots = jnp.einsum("brihd,brjhd->bhij", q, k) * scale * tie_scale
    if qm is not None:
        pair = qm[:, None, :, None] & km[:, None, None, :]
        dots = jnp.where(pair, dots, -1e9)
    p = jax.nn.softmax(dots.astype(jnp.float32), axis=-1).astype(q.dtype)
    return jnp.einsum("bhij,brjhd->brihd", p, v)


def tied_inputs(shape, ragged=False, masked=True, seed=0):
    b, r, n, h, d = shape
    ks = jax.random.split(jax.random.key(seed), 3)
    q = jax.random.normal(ks[0], shape)
    k = jax.random.normal(ks[1], shape)
    v = jax.random.normal(ks[2], shape)
    if not masked:
        return q, k, v, None, None, float(r) ** -0.5
    # column padding (every row agrees — what MSA length padding is),
    # optionally one fully-masked row (abstains entirely)
    rows = jnp.ones((b, r, n), bool).at[:, :, max(1, n - 5):].set(False)
    if ragged:
        rows = rows.at[:, 1].set(False)
    q = jnp.where(rows[..., None, None], q, 0)
    k = jnp.where(rows[..., None, None], k, 0)
    v = jnp.where(rows[..., None, None], v, 0)
    n_rows = jnp.maximum((rows.any(-1) & rows.any(-1)).sum(-1), 1)
    tie_scale = (n_rows.astype(jnp.float32) ** -0.5)[:, None, None, None]
    return q, k, v, rows.any(1), rows.any(1), tie_scale


# ---------------------------------------------------- axial kernel oracle


@pytest.mark.parametrize(
    "shape",
    [
        (2, 2, 128, 128, 32),  # exact one-block tiles
        (1, 2, 200, 200, 16),  # odd length: padded keys + sliced queries
        (2, 1, 37, 91, 8),  # rectangular (cross-shape), tiny blocks
    ],
)
def test_fused_attention_matches_dense(shape):
    b, h, nq, nk, d = shape
    ks = jax.random.split(jax.random.key(1), 3)
    q = jax.random.normal(ks[0], (b, h, nq, d))
    k = jax.random.normal(ks[1], (b, h, nk, d))
    v = jax.random.normal(ks[2], (b, h, nk, d))
    q_mask = jnp.ones((b, nq), bool).at[:, nq - 3:].set(False)
    kv_mask = jnp.ones((b, nk), bool).at[:, max(1, nk - 7):].set(False)
    out = fused_attention(
        q, k, v, q_mask=q_mask, kv_mask=kv_mask, sm_scale=d**-0.5
    )
    ref = dense_attention(
        q, k, v, q_mask=q_mask, kv_mask=kv_mask, scale=d**-0.5
    )
    assert out.shape == ref.shape
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=ATOL)


def test_fused_attention_unmasked_and_inside_jit():
    b, h, n, d = 1, 2, 160, 32  # non-block length, no masks at all
    ks = jax.random.split(jax.random.key(2), 3)
    q, k, v = (jax.random.normal(x, (b, h, n, d)) for x in ks)
    out = jax.jit(
        lambda q, k, v: fused_attention(q, k, v, sm_scale=d**-0.5)
    )(q, k, v)
    ref = dense_attention(q, k, v, scale=d**-0.5)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=ATOL)


def test_fused_attention_grad_matches_dense():
    b, h, n, d = 1, 2, 200, 16  # odd length: grads flow through padding
    ks = jax.random.split(jax.random.key(3), 3)
    q, k, v = (jax.random.normal(x, (b, h, n, d)) for x in ks)
    mask = jnp.ones((b, n), bool).at[:, 180:].set(False)

    def loss(fn):
        return jax.grad(
            lambda q, k, v: jnp.sum(
                jnp.sin(fn(q, k, v, kv_mask=mask, sm_scale=d**-0.5))
            ),
            argnums=(0, 1, 2),
        )(q, k, v)

    grads_f = loss(lambda *a, **kw: fused_attention(*a, **kw))
    grads_d = loss(
        lambda q, k, v, kv_mask, sm_scale: dense_attention(
            q, k, v, kv_mask=kv_mask, scale=sm_scale
        )
    )
    for gf, gd in zip(grads_f, grads_d):
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gd), atol=ATOL)


def test_fused_attention_fully_masked_batch_row_is_finite():
    # one batch entry with EVERY key masked: the l >= 1e-30 guard must
    # yield finite (zero-ish) output, not NaN
    b, h, n, d = 2, 1, 64, 8
    q = jax.random.normal(jax.random.key(4), (b, h, n, d))
    mask = jnp.ones((b, n), bool).at[0].set(False)
    out = fused_attention(q, q, q, kv_mask=mask, sm_scale=d**-0.5)
    assert bool(jnp.all(jnp.isfinite(out)))


# ------------------------------------------------- tied-row kernel oracle


@pytest.mark.parametrize(
    "shape,ragged,masked",
    [
        ((2, 3, 24, 2, 16), False, True),  # column padding
        ((1, 5, 140, 2, 8), False, True),  # odd length, padded blocks
        ((2, 4, 33, 1, 8), True, True),  # a fully-masked row abstains
        ((1, 4, 48, 2, 8), False, False),  # no masks at all
    ],
)
def test_tied_row_matches_dense(shape, ragged, masked):
    q, k, v, qm, km, tie_scale = tied_inputs(shape, ragged, masked)
    d = shape[-1]
    out = tied_row_attention(
        q, k, v, q_mask=qm, kv_mask=km, sm_scale=d**-0.5,
        tie_scale=tie_scale,
    )
    ref = dense_tied(q, k, v, qm, km, tie_scale, d**-0.5)
    valid = (
        jnp.broadcast_to(qm[:, None, :, None, None], ref.shape)
        if qm is not None else jnp.ones_like(ref, bool)
    )
    err = jnp.max(jnp.abs(jnp.where(valid, out - ref, 0)))
    assert float(err) < ATOL


def test_tied_row_grad_matches_dense():
    shape = (1, 4, 60, 2, 8)
    q, k, v, qm, km, tie_scale = tied_inputs(shape, ragged=True)
    d = shape[-1]
    valid = jnp.broadcast_to(
        qm[:, None, :, None, None],
        (shape[0], shape[1], shape[2], shape[3], shape[4]),
    )

    def grads(fn):
        def inner(q_, k_, v_):
            return jnp.sum(jnp.sin(fn(q_, k_, v_)) * valid)

        return jax.grad(inner, argnums=(0, 1, 2))(q, k, v)

    gf = grads(
        lambda a, b, c: tied_row_attention(
            a, b, c, q_mask=qm, kv_mask=km, sm_scale=d**-0.5,
            tie_scale=tie_scale,
        )
    )
    gd = grads(
        lambda a, b, c: dense_tied(a, b, c, qm, km, tie_scale, d**-0.5)
    )
    for x, y in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y), atol=ATOL)


# ------------------------------------------------------- policy switchboard


def test_policy_parse_describe_roundtrip():
    assert KernelPolicy().describe() == "auto"
    p = parse_policy("tied_row=pallas, axial=dense")
    assert p.tied_row == "pallas" and p.axial == "dense"
    assert p.describe() == "tied_row=pallas,axial=dense"
    assert parse_policy("") == KernelPolicy()
    assert parse_policy("auto") == KernelPolicy()
    with pytest.raises(ValueError):
        parse_policy("tied_row=fast")  # unknown value
    with pytest.raises(ValueError):
        parse_policy("warp=pallas")  # unknown field
    with pytest.raises(ValueError):
        KernelPolicy(axial="bogus")


def test_policy_env_and_context_precedence(monkeypatch):
    monkeypatch.delenv("AF2TPU_KERNELS", raising=False)
    assert resolve_tied_row() == "dense"  # auto off-TPU
    assert resolve_axial() == "stock"
    assert resolve_block_sparse() == "jnp"
    monkeypatch.setenv("AF2TPU_KERNELS", "tied_row=pallas,block_sparse=splash")
    assert resolve_tied_row() == "pallas"
    assert resolve_block_sparse() == "splash"
    # an explicit context wins over the env
    with use_kernel_policy(parse_policy("tied_row=dense,axial=pallas")):
        assert resolve_tied_row() == "dense"
        assert resolve_axial() == "pallas"
    assert resolve_tied_row() == "pallas"  # env restored


def test_attention_tied_path_dispatch(monkeypatch):
    """The tied branch must route through the fused kernel exactly when the
    policy says pallas and dropout is inactive — and produce the dense
    numbers (valid region) when it does."""
    from alphafold2_tpu.ops.attention import Attention
    from alphafold2_tpu.ops.pallas import tied_row as tied_mod

    x = jax.random.normal(jax.random.key(5), (4, 24, 32))  # (B*R, n, d), R=2
    mask = jnp.ones((4, 24), bool).at[:, 20:].set(False)
    attn = Attention(dim=32, heads=2, dim_head=16)
    params = attn.init(jax.random.key(6), x, mask=mask, tie_dim=2)
    dense_out = attn.apply(params, x, mask=mask, tie_dim=2)

    calls = {"n": 0}
    real = tied_mod.tied_row_attention

    def spy(*a, **kw):
        calls["n"] += 1
        return real(*a, **kw)

    monkeypatch.setattr(tied_mod, "tied_row_attention", spy)
    with use_kernel_policy(parse_policy("tied_row=pallas")):
        fused_out = attn.apply(params, x, mask=mask, tie_dim=2)
    assert calls["n"] == 1
    valid = np.asarray(mask)[:, :, None]
    assert np.max(np.abs(np.asarray(fused_out - dense_out)) * valid) < ATOL

    # dense policy (and the off-TPU auto default): kernel never touched
    attn.apply(params, x, mask=mask, tie_dim=2)
    with use_kernel_policy(parse_policy("tied_row=dense")):
        attn.apply(params, x, mask=mask, tie_dim=2)
    assert calls["n"] == 1

    # active attention-weight dropout needs materialized probabilities:
    # the kernel must NOT be taken even under a pallas policy
    drop = Attention(dim=32, heads=2, dim_head=16, dropout=0.5)
    params_d = drop.init(jax.random.key(7), x, tie_dim=2)
    with use_kernel_policy(parse_policy("tied_row=pallas")):
        out = drop.apply(
            params_d, x, tie_dim=2, deterministic=False,
            rngs={"dropout": jax.random.key(8)},
        )
    assert calls["n"] == 1 and bool(jnp.all(jnp.isfinite(out)))


def test_axial_module_parity_under_policy():
    """AxialAttention's grid route under axial=pallas: values and param
    grads match the dense route on the valid region."""
    from alphafold2_tpu.ops.attention import AxialAttention

    x = jax.random.normal(jax.random.key(9), (2, 12, 20, 32))
    mask = (
        jnp.ones((2, 12, 20), bool)
        .at[:, :, 17:].set(False)
        .at[:, 10:, :].set(False)
    )
    ax = AxialAttention(dim=32, heads=2, dim_head=16)
    params = ax.init(jax.random.key(10), x, mask=mask)
    dense_out = ax.apply(params, x, mask=mask)
    with use_kernel_policy(parse_policy("axial=pallas")):
        fused_out = ax.apply(params, x, mask=mask)
    valid = np.asarray(mask)[..., None]
    assert np.max(np.abs(np.asarray(fused_out - dense_out)) * valid) < ATOL

    def grads(policy):
        def inner(p):
            ctx = (
                use_kernel_policy(parse_policy(policy))
                if policy else use_kernel_policy(None)
            )
            with ctx:
                o = ax.apply(p, x, mask=mask)
            return jnp.sum(jnp.sin(o) * mask[..., None])

        return jax.tree.leaves(jax.grad(inner)(params))

    for gd, gf in zip(grads(None), grads("axial=pallas")):
        np.testing.assert_allclose(np.asarray(gd), np.asarray(gf), atol=ATOL)


def test_sparse_backend_policy_registration(monkeypatch):
    """SparseAttention's backend resolves through the same switchboard:
    explicit use_pallas > config.backend > KernelPolicy > auto."""
    from alphafold2_tpu.ops import sparse as sparse_mod
    from alphafold2_tpu.ops.sparse import BlockSparseConfig, SparseAttention

    def impl_name(module):
        # bound via a parent-less setup: _impl only reads config/attrs
        return module._impl().__name__

    base = dict(dim=32, heads=2, dim_head=16, seq_len=64)
    monkeypatch.delenv("AF2TPU_KERNELS", raising=False)
    assert impl_name(SparseAttention(**base)) == "block_sparse_attention"
    with use_kernel_policy(parse_policy("block_sparse=pallas")):
        assert (
            impl_name(SparseAttention(**base))
            == "block_sparse_attention_pallas"
        )
    with use_kernel_policy(parse_policy("block_sparse=splash")):
        assert (
            impl_name(SparseAttention(**base))
            == "block_sparse_attention_splash"
        )
        # explicit module choices still win over the policy
        assert (
            impl_name(SparseAttention(**base, use_pallas=True))
            == "block_sparse_attention_pallas"
        )
        assert (
            impl_name(
                SparseAttention(
                    **base, config=BlockSparseConfig(backend="jnp")
                )
            )
            == "block_sparse_attention"
        )
