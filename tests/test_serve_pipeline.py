"""Pipelined dispatch tests (serve/pipeline.py + the engine's staged path).

The acceptance contract pinned here: the pipelined dispatch path must be
byte-identical to the serial path for the same (seq, seed) — including
batch-padded slots and requests admitted into an in-flight formation —
while faults in any stage surface as structured error results (the
completion worker never wedges), donation intent demonstrably reaches
XLA, and the new device_idle_frac metric / "pipeline" record key are
computed and gated the way bench.py and observe/regress.py claim.
"""

import threading

import numpy as np
import pytest

from alphafold2_tpu.config import (
    Config,
    DataConfig,
    ModelConfig,
    ServeConfig,
)
from alphafold2_tpu.observe.regress import comparable_reason
from alphafold2_tpu.observe.tracing import (
    Tracer,
    device_idle_fraction,
    merge_intervals,
)
from alphafold2_tpu.serve import (
    AsyncServeFrontend,
    DispatchHandle,
    FaultPlan,
    PipelineBatch,
    ServeEngine,
    ServeRequest,
    formation_ripe,
)


def _cfg(buckets=(8, 16), max_batch=2, **serve_kw):
    serve_kw.setdefault("mds_iters", 10)
    return Config(
        model=ModelConfig(dim=32, depth=1, heads=2, dim_head=16,
                          max_seq_len=3 * max(buckets), bfloat16=False),
        data=DataConfig(msa_depth=2),
        serve=ServeConfig(buckets=buckets, max_batch=max_batch, **serve_kw),
    )


@pytest.fixture(scope="module")
def engine():
    """Pipelined engine (the config default: depth 2)."""
    eng = ServeEngine(_cfg())
    assert eng.pipeline is not None and eng.pipeline_desc == "depth2"
    return eng


# ------------------------------------------------------- pure-host pieces


def test_formation_ripe():
    assert not formation_ripe(0, 4, 99.0, 0.05)  # empty never ripens
    assert formation_ripe(4, 4, 0.0, 0.05)  # full fires without dwell
    assert not formation_ripe(1, 4, 0.01, 0.05)  # under-full, inside dwell
    assert formation_ripe(1, 4, 0.05, 0.05)  # dwell expiry fires partial
    assert formation_ripe(1, 0, 0.0, 9.0)  # degenerate fill clamps to 1


def test_pipeline_batch_join_seal_semantics():
    b = PipelineBatch(8, [("r0",)], fill=3)
    assert b.try_join(("r1",)) and b.try_join(("r2",))
    assert not b.try_join(("r3",))  # at fill
    assert b.next_member(0) == ("r0",) and b.next_member(2) == ("r2",)
    assert b.next_member(3) is None  # drained: seals the formation
    assert b.sealed and not b.try_join(("late",))
    assert b.members == [("r0",), ("r1",), ("r2",)]


def test_dispatch_handle_resolution_and_callbacks():
    h = DispatchHandle(PipelineBatch(8, [], fill=1))
    with pytest.raises(TimeoutError):
        h.result(timeout=0.01)
    seen = []
    h.add_done_callback(seen.append)
    h.add_done_callback(lambda _r: 1 / 0)  # must not break resolution
    h._resolve(["done"])
    assert h.done() and h.result(0) == ["done"]
    assert seen == [["done"]]
    h.add_done_callback(seen.append)  # post-resolution: runs immediately
    assert seen == [["done"], ["done"]]


def test_device_idle_fraction_from_synthetic_spans():
    us = 1e6

    def span(name, start_s, dur_s):
        return {"ph": "X", "name": name, "ts": start_s * us,
                "dur": dur_s * us}

    # dispatch 0-1s, fetch 1.5-2s: window 2s, busy 1.5s -> idle 0.25
    events = [
        span("serve.dispatch", 0.0, 1.0),
        span("serve.device_get", 1.5, 0.5),
        span("serve.featurize", 0.0, 2.0),  # host span: not device time
    ]
    out = device_idle_fraction(events)
    assert out["dispatches"] == 1
    assert out["window_s"] == pytest.approx(2.0)
    assert out["busy_s"] == pytest.approx(1.5)
    assert out["device_idle_frac"] == pytest.approx(0.25)
    # no serve.dispatch spans -> no window to judge
    assert device_idle_fraction([span("serve.device_get", 0, 1)]) is None
    assert device_idle_fraction([]) is None
    # overlapping spans merge rather than double-count
    assert merge_intervals([(0, 2), (1, 3), (5, 6)]) == [(0, 3), (5, 6)]


def test_regress_refuses_pipeline_variant_cross_comparison():
    base = {"metric": "serve cpu", "device": "cpu", "pipeline": "off",
            "value": 10.0}
    cur = dict(base, pipeline="depth2")
    reason = comparable_reason(cur, base)
    assert reason is not None and "pipeline" in reason
    assert comparable_reason(dict(base), base) is None


# ------------------------------------------------- real-engine contracts


def test_pipelined_byte_identical_to_serial_with_padded_slots(engine):
    """Same (seq, seed) stream through the pipelined and the serial path:
    identical bytes out, including the chunk that dispatches with a
    batch-padding slot (3 requests at max_batch=2) and a second bucket."""
    seqs = ["ACDEFG", "MKVLIT", "WY", "ACDEFGHKLMNP"]
    reqs = [ServeRequest(s, seed=i) for i, s in enumerate(seqs)]
    serial_eng = ServeEngine(
        _cfg(pipeline_depth=0), params=engine.params
    )
    assert serial_eng.pipeline is None and serial_eng.pipeline_desc == "off"

    piped = engine.predict_many(reqs)
    serial = serial_eng.predict_many(
        [ServeRequest(s, seed=i) for i, s in enumerate(seqs)]
    )
    assert [r.status for r in piped] == ["ok"] * len(seqs)
    # the padded chunk really dispatched with a dummy slot
    assert engine.counters.get("serve.padded_slots") >= 1
    for p, s in zip(piped, serial):
        assert p.seq == s.seq and p.bucket == s.bucket
        assert p.atom14.tobytes() == s.atom14.tobytes()
        assert p.backbone.tobytes() == s.backbone.tobytes()
        assert p.weights.tobytes() == s.weights.tobytes()
    # pipelined timing semantics still span arrival -> completion
    assert all(
        r.latency_s == pytest.approx(r.queue_wait_s + r.dispatch_s)
        for r in piped
    )


def test_inflight_admitted_request_byte_identical(engine, monkeypatch):
    """A request joined into an in-flight formation (continuous batching)
    comes back byte-identical to the same (seq, seed) served serially in
    the same two-request batch."""
    eng = ServeEngine(_cfg(), params=engine.params)
    gate = threading.Event()
    started = threading.Event()
    orig = ServeEngine._featurize_one

    def gated(self, bucket, req):
        started.set()
        assert gate.wait(30), "test gate never opened"
        return orig(self, bucket, req)

    monkeypatch.setattr(ServeEngine, "_featurize_one", gated)
    r1, r2 = ServeRequest("ACDEFG", seed=3), ServeRequest("MKVLIT", seed=4)
    handle = eng.dispatch_batch_async(8, [r1], joinable=True)
    assert started.wait(30)  # host stage is inside member 0's featurize
    assert handle.try_join(r2)  # formation still open: joins in flight
    gate.set()
    got = handle.result(timeout=180)
    monkeypatch.undo()
    assert [r.status for r in got] == ["ok", "ok"]
    assert not handle.try_join(ServeRequest("WY", seed=5))  # sealed

    serial_eng = ServeEngine(_cfg(pipeline_depth=0), params=engine.params)
    serial = serial_eng.dispatch_batch(8, [
        ServeRequest("ACDEFG", seed=3), ServeRequest("MKVLIT", seed=4),
    ])
    for p, s in zip(got, serial):
        assert p.seq == s.seq
        assert p.atom14.tobytes() == s.atom14.tobytes()
        assert p.weights.tobytes() == s.weights.tobytes()


def test_donation_takes_effect_for_standard_buckets(engine):
    """The donation audit (satellite): every standard-bucket executable
    asked XLA to donate the four request buffers, and XLA's unusable-
    donation report (int/bool inputs cannot alias f32 outputs) was
    captured into the compile record instead of silently suppressed."""
    assert engine.compile_records, "fixture engine has compiled"
    for rec in engine.compile_records:
        assert rec["donated_args"] == 4  # seq, msa, mask, msa_mask
        # all four are int32/bool feature buffers: XLA reports every one
        # unaliasable — donation still releases them during execution
        assert rec["donation_unusable"] == 4

    off = ServeEngine(
        _cfg(donate_buffers=False), params=engine.params
    )
    off.predict_many([ServeRequest("ACDEFG", seed=0)])
    assert off.compile_records
    for rec in off.compile_records:
        assert "donated_args" not in rec
        assert "donation_unusable" not in rec


@pytest.mark.parametrize("stage", ["transfer", "compute", "fetch"])
def test_stage_fault_yields_structured_errors_not_a_wedge(engine, stage):
    """An injected fault in any pipeline stage resolves the future with
    structured per-request errors — the completion worker never wedges —
    and the very next dispatch succeeds (fault budget expired)."""
    plan = FaultPlan(fail_bucket=8, times=1, fail_stage=stage)
    eng = ServeEngine(_cfg(), params=engine.params, faults=plan)
    out = eng.predict_many([ServeRequest("ACDEFG", seed=0),
                            ServeRequest("MK", seed=1)])
    assert [r.status for r in out] == ["error", "error"]
    assert all("InjectedFault" in r.error and stage in r.error for r in out)
    assert plan.fired == [{"dispatch": 1, "bucket": 8, "stage": stage}]
    assert eng.stats()["serve.dispatch_errors"] == 1
    ok = eng.predict_many([ServeRequest("ACDEFG", seed=0)])[0]
    assert ok.ok and np.all(np.isfinite(ok.atom14))


def test_pipeline_emits_device_spans_and_batch_marker(engine):
    """The pipelined path's spans feed device_idle_fraction: dispatch and
    device_get spans carry dispatch_index, the retroactive serve.batch
    span is marked pipelined, and the idle fraction is computable."""
    tracer = Tracer(enabled=True)
    eng = ServeEngine(_cfg(), params=engine.params, tracer=tracer)
    eng.predict_many([ServeRequest("ACDEFG", seed=0),
                      ServeRequest("ACDEFGHKLMNP", seed=1)])
    events = tracer.events()
    spans = {e["name"] for e in events if e.get("ph") == "X"}
    assert {"serve.featurize", "serve.device_put", "serve.dispatch",
            "serve.device_get", "serve.unpad", "serve.batch"} <= spans
    batch_spans = [e for e in events if e.get("name") == "serve.batch"]
    assert batch_spans and all(
        (e.get("args") or {}).get("pipelined") for e in batch_spans
    )
    dispatch_args = [
        (e.get("args") or {}) for e in events
        if e.get("name") == "serve.dispatch"
    ]
    assert dispatch_args and all(
        a.get("dispatch_index") for a in dispatch_args
    )
    idle = device_idle_fraction(events)
    assert idle is not None and 0.0 <= idle["device_idle_frac"] <= 1.0
    assert idle["dispatches"] == len(dispatch_args)


def test_depth_one_pipeline_and_backpressure(engine):
    """depth=1 serializes in-flight batches (submit blocks until the
    previous batch completes) but still produces correct results."""
    eng = ServeEngine(_cfg(pipeline_depth=1), params=engine.params)
    assert eng.pipeline_desc == "depth1"
    out = eng.predict_many(
        [ServeRequest("ACDEFG", seed=i) for i in range(5)]
    )
    assert all(r.ok for r in out)
    with pytest.raises(ValueError):
        ServeEngine(_cfg(pipeline_depth=-1), params=engine.params)


def test_frontend_inflight_admission_joins_forming_batch(
    engine, monkeypatch
):
    """A request arriving while a bucket's formation sits in the host
    stage joins that in-flight batch (no queue slot, no dwell wait) and
    resolves from the same dispatch."""
    eng = ServeEngine(_cfg(dwell_ms=0.0), params=engine.params)
    gate = threading.Event()
    started = threading.Event()
    orig = ServeEngine._featurize_one

    def gated(self, bucket, req):
        started.set()
        assert gate.wait(30), "test gate never opened"
        return orig(self, bucket, req)

    monkeypatch.setattr(ServeEngine, "_featurize_one", gated)
    fe = AsyncServeFrontend(eng, start=False)
    assert fe.inflight_admission  # engine is pipelined + config default on
    h1 = fe.submit(ServeRequest("ACDEFG", seed=1))
    assert fe.pump() == 1  # zero dwell: the single request dispatches
    assert started.wait(30)
    h2 = fe.submit(ServeRequest("MKVLIT", seed=2))  # joins in flight
    assert fe.stats()["sched.inflight_admitted"] == 1
    gate.set()
    out1, out2 = h1.result(180), h2.result(180)
    monkeypatch.undo()
    assert out1.ok and out2.ok
    assert fe.stats()["sched.dispatches"] == 1  # one shared dispatch
    assert fe.stats()["sched.batched_requests"] == 2
    # the admitted request's result is byte-identical to the serial batch
    serial_eng = ServeEngine(_cfg(pipeline_depth=0), params=engine.params)
    serial = serial_eng.dispatch_batch(8, [
        ServeRequest("ACDEFG", seed=1), ServeRequest("MKVLIT", seed=2),
    ])
    assert out2.atom14.tobytes() == serial[1].atom14.tobytes()


def test_inflight_admission_disabled_by_config(engine):
    eng = ServeEngine(
        _cfg(inflight_admission=False), params=engine.params
    )
    fe = AsyncServeFrontend(eng, start=False)
    assert not fe.inflight_admission


def test_predict_many_overlaps_host_and_device(engine):
    """The tentpole's mechanism, pinned structurally: with several batches
    in flight, some batch's host stage (featurize/device_put) runs inside
    another batch's device window — the trace intervals overlap."""
    tracer = Tracer(enabled=True)
    eng = ServeEngine(_cfg(), params=engine.params, tracer=tracer)
    eng.warmup()  # keep compiles out of the overlap window
    reqs = [ServeRequest("ACDEFG", seed=i) for i in range(8)]
    eng.predict_many(reqs)
    host, dev = {}, {}
    for e in tracer.events():
        if e.get("ph") != "X":
            continue
        args = e.get("args") or {}
        idx = args.get("dispatch_index")
        if idx is None:
            continue
        iv = (e["ts"] / 1e6, (e["ts"] + e.get("dur", 0)) / 1e6)
        if e["name"] in ("serve.featurize", "serve.device_put"):
            host.setdefault(idx, []).append(iv)
        elif e["name"] in ("serve.dispatch", "serve.device_get"):
            dev.setdefault(idx, []).append(iv)
    assert len(dev) == 4  # 8 requests / max_batch 2
    overlap = 0.0
    for i, dev_ivs in dev.items():
        others = merge_intervals(
            [iv for j, ivs in host.items() if j != i for iv in ivs]
        )
        for ds, de in merge_intervals(dev_ivs):
            for hs, he in others:
                overlap += max(0.0, min(de, he) - max(ds, hs))
    assert overlap > 0.0, "no host stage ran inside another device window"


def test_close_shuts_down_stage_workers(engine):
    eng = ServeEngine(_cfg(), params=engine.params)
    assert eng.predict_many([ServeRequest("AC", seed=0)])[0].ok
    eng.close()
    with pytest.raises(RuntimeError):  # executors refuse post-shutdown work
        eng.dispatch_batch_async(8, [ServeRequest("AC", seed=1)])
