"""Alignment/metric oracle tests: Kabsch recovers a known rigid transform,
metrics hit exact values on identity and known perturbations."""

import jax
import jax.numpy as jnp
import numpy as np

from alphafold2_tpu.utils import GDT, Kabsch, RMSD, TMscore, kabsch, rmsd


def _random_rotation(key):
    m = jax.random.normal(key, (3, 3))
    q, r = jnp.linalg.qr(m)
    q = q * jnp.sign(jnp.diagonal(r))
    # ensure a proper rotation
    det = jnp.linalg.det(q)
    return q.at[:, 0].multiply(jnp.sign(det))


def test_kabsch_recovers_rigid_transform():
    key = jax.random.key(0)
    k1, k2 = jax.random.split(key)
    A = jax.random.normal(k1, (3, 32))
    R = _random_rotation(k2)
    B = R @ A + jnp.array([[1.0], [2.0], [3.0]])
    A_, B_ = Kabsch(A, B)
    assert A_.shape == A.shape
    assert float(rmsd(A_[None], B_[None])[0]) < 1e-2  # float32 SVD precision


def test_kabsch_batched():
    key = jax.random.key(1)
    A = jax.random.normal(key, (4, 3, 16))
    R = _random_rotation(jax.random.key(2))
    B = jnp.einsum("ij,bjn->bin", R, A)
    A_, B_ = kabsch(A, B)
    assert A_.shape == (4, 3, 16)
    assert np.all(np.asarray(rmsd(A_, B_)) < 1e-2)  # float32 SVD precision


def test_rmsd_exact():
    a = jnp.zeros((1, 3, 10))
    b = jnp.ones((1, 3, 10))
    assert np.isclose(float(RMSD(a, b)[0]), 1.0)
    # unbatched input auto-expands
    assert np.isclose(float(RMSD(a[0], b[0])[0]), 1.0)


def test_gdt_identity_and_modes():
    a = jax.random.normal(jax.random.key(0), (1, 3, 8))
    assert np.isclose(float(GDT(a, a)[0]), 1.0)
    # one point displaced by 3A: within TS cutoffs 4,8 but not 1,2
    b = a.at[:, :, 0].add(jnp.array([3.0, 0, 0])[None, :])
    ts = float(GDT(a, b, mode="TS")[0])
    expected_ts = (7 / 8 + 7 / 8 + 1.0 + 1.0) / 4
    assert np.isclose(ts, expected_ts, atol=1e-6)
    ha = float(GDT(a, b, mode="HA")[0])
    expected_ha = (7 / 8 + 7 / 8 + 7 / 8 + 1.0) / 4
    assert np.isclose(ha, expected_ha, atol=1e-6)
    # weighted
    GDT(a, b, weights=[1, 1, 2, 4])


def test_tmscore_identity():
    a = jax.random.normal(jax.random.key(3), (2, 3, 64))
    assert np.allclose(np.asarray(TMscore(a, a)), 1.0)
    b = a + 100.0  # far apart -> score near 0 ... but rigid shift: TM uses raw dist
    assert np.all(np.asarray(TMscore(a, b)) < 0.05)


def test_metrics_accept_numpy():
    a = np.random.RandomState(0).randn(2, 3, 8)
    b = np.random.RandomState(1).randn(2, 3, 8)
    for fn in (RMSD, TMscore, GDT):
        out = np.asarray(fn(a, b))
        assert out.shape == (2,)
        assert np.all(np.isfinite(out))
