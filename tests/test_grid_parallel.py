"""2D (rows x cols) pair-grid sharding: exactness of each axial pass and its
gradients against the dense oracle, on the 8-virtual-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from alphafold2_tpu.parallel.grid_parallel import (
    grid_axial_attention,
    make_grid_mesh,
)

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 virtual devices"
)

B, N, HEADS, D = 2, 8, 2, 4


def _qkv(key):
    ks = jax.random.split(key, 3)
    shape = (B, N, N, HEADS, D)
    return tuple(jax.random.normal(k, shape) for k in ks)


def _mask():
    m = jnp.ones((B, N, N), bool)
    return m.at[:, -2:, :].set(False).at[:, :, -1].set(False)


@pytest.mark.parametrize("attend_axis", [1, 2])
def test_sharded_matches_dense(attend_axis):
    q, k, v = _qkv(jax.random.key(0))
    mask = _mask()
    mesh = make_grid_mesh(2, 2, 2)
    dense = grid_axial_attention(q, k, v, mask, mesh=None, attend_axis=attend_axis)
    sharded = jax.jit(
        lambda q, k, v: grid_axial_attention(
            q, k, v, mask, mesh=mesh, attend_axis=attend_axis
        )
    )(q, k, v)
    # compare only at valid *query* positions: fully-masked key rows produce
    # uniform-softmax garbage at padded queries in both paths, but the
    # accumulation order differs
    valid = np.asarray(mask)[..., None, None]
    np.testing.assert_allclose(
        np.asarray(sharded) * valid, np.asarray(dense) * valid, atol=2e-5
    )


@pytest.mark.parametrize("attend_axis", [1, 2])
def test_grads_match_dense(attend_axis):
    q, k, v = _qkv(jax.random.key(1))
    mask = _mask()
    mesh = make_grid_mesh(2, 2, 2)
    w = jax.random.normal(jax.random.key(2), q.shape)  # fixed cotangent probe
    valid = _mask()[..., None, None]

    def loss(mesh_arg):
        def f(q, k, v):
            out = grid_axial_attention(
                q, k, v, mask, mesh=mesh_arg, attend_axis=attend_axis
            )
            return jnp.sum(jnp.where(valid, out * w, 0.0))

        return f

    gd = jax.grad(loss(None), argnums=(0, 1, 2))(q, k, v)
    gs = jax.jit(jax.grad(loss(mesh), argnums=(0, 1, 2)))(q, k, v)
    for a, b in zip(gs, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5)


def test_spr_only_grid():
    """Degenerate 1D layouts of the same mesh type still work (spc=1)."""
    q, k, v = _qkv(jax.random.key(3))
    mesh = make_grid_mesh(2, 4, 1)
    dense = grid_axial_attention(q, k, v, attend_axis=1)
    sharded = jax.jit(
        lambda q, k, v: grid_axial_attention(q, k, v, mesh=mesh, attend_axis=1)
    )(q, k, v)
    np.testing.assert_allclose(np.asarray(sharded), np.asarray(dense), atol=2e-5)


def test_grid_train_step_matches_single_device():
    """Full training step with model.grid_parallel=True over a (2, 2, 2)
    grid mesh == the single-device step (same params, same loss)."""
    from alphafold2_tpu.config import Config, DataConfig, MeshConfig, ModelConfig, TrainConfig
    from alphafold2_tpu.data.pipeline import SyntheticDataset
    from alphafold2_tpu.train.loop import (
        build_model, device_put_batch, init_state, make_train_step,
    )

    cfg = Config(
        model=ModelConfig(dim=32, depth=1, heads=2, dim_head=16,
                          max_seq_len=64, bfloat16=False, grid_parallel=True),
        mesh=MeshConfig(data_parallel=2, grid_rows=2, grid_cols=2),
        data=DataConfig(crop_len=16, msa_depth=2, msa_len=16, batch_size=2,
                        min_len_filter=8),
        train=TrainConfig(gradient_accumulate_every=1, warmup_steps=2),
    )
    batch = next(iter(SyntheticDataset(cfg.data, seed=4)))
    model = build_model(cfg)

    state1 = init_state(cfg, model, batch)
    step1 = make_train_step(model, mesh=None)
    s1, m1 = step1(state1, device_put_batch(batch), jax.random.key(9))

    mesh = make_grid_mesh(2, 2, 2)
    state2 = init_state(cfg, model, batch)
    step2 = make_train_step(model, mesh=mesh)
    s2, m2 = step2(state2, device_put_batch(batch, mesh), jax.random.key(9))

    assert np.isclose(float(m1["loss"]), float(m2["loss"]), rtol=1e-4), (
        float(m1["loss"]), float(m2["loss"]),
    )
    for a, b in zip(jax.tree.leaves(s1.params), jax.tree.leaves(s2.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


def test_indivisible_axis_raises():
    # N/spr = 4 rows per device, spc = 2 -> fine; but N=6 local rows 3 is
    # not divisible by spc=2 for the transpose
    n = 6
    shape = (B, n, n, HEADS, D)
    q = k = v = jnp.zeros(shape)
    mesh = make_grid_mesh(2, 2, 2)
    with pytest.raises(ValueError, match="must divide"):
        jax.jit(
            lambda q, k, v: grid_axial_attention(q, k, v, mesh=mesh, attend_axis=2)
        )(q, k, v)
