"""2D (rows x cols) pair-grid sharding: exactness of each axial pass and its
gradients against the dense oracle, on the 8-virtual-device CPU mesh."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from alphafold2_tpu.parallel.grid_parallel import (
    grid_axial_attention,
    make_grid_mesh,
)

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 virtual devices"
)

B, N, HEADS, D = 2, 8, 2, 4


def _qkv(key):
    ks = jax.random.split(key, 3)
    shape = (B, N, N, HEADS, D)
    return tuple(jax.random.normal(k, shape) for k in ks)


def _mask():
    m = jnp.ones((B, N, N), bool)
    return m.at[:, -2:, :].set(False).at[:, :, -1].set(False)


@pytest.mark.parametrize("attend_axis", [1, 2])
def test_sharded_matches_dense(attend_axis):
    q, k, v = _qkv(jax.random.key(0))
    mask = _mask()
    mesh = make_grid_mesh(2, 2, 2)
    dense = grid_axial_attention(q, k, v, mask, mesh=None, attend_axis=attend_axis)
    sharded = jax.jit(
        lambda q, k, v: grid_axial_attention(
            q, k, v, mask, mesh=mesh, attend_axis=attend_axis
        )
    )(q, k, v)
    # compare only at valid *query* positions: fully-masked key rows produce
    # uniform-softmax garbage at padded queries in both paths, but the
    # accumulation order differs
    valid = np.asarray(mask)[..., None, None]
    np.testing.assert_allclose(
        np.asarray(sharded) * valid, np.asarray(dense) * valid, atol=2e-5
    )


@pytest.mark.parametrize("attend_axis", [1, 2])
def test_grads_match_dense(attend_axis):
    q, k, v = _qkv(jax.random.key(1))
    mask = _mask()
    mesh = make_grid_mesh(2, 2, 2)
    w = jax.random.normal(jax.random.key(2), q.shape)  # fixed cotangent probe
    valid = _mask()[..., None, None]

    def loss(mesh_arg):
        def f(q, k, v):
            out = grid_axial_attention(
                q, k, v, mask, mesh=mesh_arg, attend_axis=attend_axis
            )
            return jnp.sum(jnp.where(valid, out * w, 0.0))

        return f

    gd = jax.grad(loss(None), argnums=(0, 1, 2))(q, k, v)
    gs = jax.jit(jax.grad(loss(mesh), argnums=(0, 1, 2)))(q, k, v)
    for a, b in zip(gs, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5)


def test_spr_only_grid():
    """Degenerate 1D layouts of the same mesh type still work (spc=1)."""
    q, k, v = _qkv(jax.random.key(3))
    mesh = make_grid_mesh(2, 4, 1)
    dense = grid_axial_attention(q, k, v, attend_axis=1)
    sharded = jax.jit(
        lambda q, k, v: grid_axial_attention(q, k, v, mesh=mesh, attend_axis=1)
    )(q, k, v)
    np.testing.assert_allclose(np.asarray(sharded), np.asarray(dense), atol=2e-5)


@pytest.mark.slow
def test_grid_train_step_matches_single_device():
    """Full training step with model.grid_parallel=True over a (2, 2, 2)
    grid mesh == the single-device step (same params, same loss)."""
    from alphafold2_tpu.config import Config, DataConfig, MeshConfig, ModelConfig, TrainConfig
    from alphafold2_tpu.data.pipeline import SyntheticDataset
    from alphafold2_tpu.train.loop import (
        build_model, device_put_batch, init_state, make_train_step,
    )

    cfg = Config(
        model=ModelConfig(dim=32, depth=1, heads=2, dim_head=16,
                          max_seq_len=64, bfloat16=False, grid_parallel=True),
        mesh=MeshConfig(data_parallel=2, grid_rows=2, grid_cols=2),
        data=DataConfig(crop_len=16, msa_depth=2, msa_len=16, batch_size=2,
                        min_len_filter=8),
        train=TrainConfig(gradient_accumulate_every=1, warmup_steps=2),
    )
    batch = next(iter(SyntheticDataset(cfg.data, seed=4)))
    model = build_model(cfg)

    state1 = init_state(cfg, model, batch)
    step1 = make_train_step(model, mesh=None)
    s1, m1 = step1(state1, device_put_batch(batch), jax.random.key(9))

    mesh = make_grid_mesh(2, 2, 2)
    state2 = init_state(cfg, model, batch)
    step2 = make_train_step(model, mesh=mesh)
    s2, m2 = step2(state2, device_put_batch(batch, mesh), jax.random.key(9))

    assert np.isclose(float(m1["loss"]), float(m2["loss"]), rtol=1e-4), (
        float(m1["loss"]), float(m2["loss"]),
    )
    for a, b in zip(jax.tree.leaves(s1.params), jax.tree.leaves(s2.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


@pytest.mark.parametrize("attend_axis", [1, 2])
def test_attn_fn_hook_runs_inside_sharded_pass(attend_axis):
    """The fused-kernel hook must actually execute per device after the
    all-to-all gather: an exact jnp reimplementation fed through the hook
    reproduces the dense path, and a sentinel (zeros) proves it ran."""
    q, k, v = _qkv(jax.random.key(5))
    mask = _mask()
    mesh = make_grid_mesh(2, 2, 2)

    def exact(q2, k2, v2, m2):  # (B2, H, N, D) + (B2, N), like flash/sparse
        dots = jnp.einsum("bhid,bhjd->bhij", q2, k2) * q2.shape[-1] ** -0.5
        dots = jnp.where(m2[:, None, None, :], dots, -1e9)
        return jnp.einsum("bhij,bhjd->bhid", jax.nn.softmax(dots, -1), v2)

    dense = grid_axial_attention(q, k, v, mask, mesh=None,
                                 attend_axis=attend_axis)
    hooked = jax.jit(
        lambda q, k, v: grid_axial_attention(
            q, k, v, mask, mesh=mesh, attend_axis=attend_axis, attn_fn=exact
        )
    )(q, k, v)
    valid = np.asarray(mask)[..., None, None]
    np.testing.assert_allclose(
        np.asarray(hooked) * valid, np.asarray(dense) * valid, atol=2e-5
    )

    sentinel = jax.jit(
        lambda q, k, v: grid_axial_attention(
            q, k, v, mask, mesh=mesh, attend_axis=attend_axis,
            attn_fn=lambda q2, k2, v2, m2: jnp.zeros_like(q2),
        )
    )(q, k, v)
    np.testing.assert_array_equal(np.asarray(sentinel), 0.0)


def test_attn_fn_decline_falls_back_dense():
    # a hook returning None (flash declining the shape) must leave the
    # dense result untouched
    q, k, v = _qkv(jax.random.key(6))
    mesh = make_grid_mesh(2, 2, 2)
    dense = jax.jit(
        lambda q, k, v: grid_axial_attention(q, k, v, mesh=mesh)
    )(q, k, v)
    declined = jax.jit(
        lambda q, k, v: grid_axial_attention(
            q, k, v, mesh=mesh, attn_fn=lambda *a: None
        )
    )(q, k, v)
    np.testing.assert_allclose(np.asarray(declined), np.asarray(dense))


def test_sparse_axial_in_grid_matches_meshless():
    """AxialAttention(sparse_attn=True, grid_parallel=True): the 2D-sharded
    passes run the block-sparse kernel per device after the gather, and the
    values match the same module without a mesh (VERDICT round-1 #7)."""
    from alphafold2_tpu.ops.attention import AxialAttention
    from alphafold2_tpu.ops.sparse import BlockSparseConfig
    from alphafold2_tpu.parallel.sharding import use_mesh

    n = 16  # grid 16x16, block 4 -> 4 blocks per attended axis
    cfg = BlockSparseConfig(
        block_size=4, num_local_blocks=2, num_global_blocks=1,
        num_random_blocks=1,
    )
    mod = AxialAttention(
        dim=16, heads=2, dim_head=8, sparse_attn=True, seq_len=n,
        sparse_config=cfg, sparse_use_pallas=False, grid_parallel=True,
    )
    x = jax.random.normal(jax.random.key(7), (2, n, n, 16))
    mask = jnp.ones((2, n, n), bool).at[:, :, -2:].set(False)
    params = mod.init(jax.random.key(8), x, mask=mask)

    meshless = mod.apply(params, x, mask=mask)
    mesh = make_grid_mesh(2, 2, 2)
    with use_mesh(mesh):
        sharded = jax.jit(lambda x: mod.apply(params, x, mask=mask))(x)
    valid = np.asarray(mask)[..., None]
    np.testing.assert_allclose(
        np.asarray(sharded) * valid, np.asarray(meshless) * valid, atol=2e-5
    )


@pytest.mark.slow
def test_sparse_grid_768_crop_step():
    """The 768-crop story (grid_parallel.py module docstring): one sparse
    axial pass over a (1, 768, 768) grid on the 8-virtual-device mesh.
    Dense logits for one pass would be 768^2 * 768 * 4B ~ 1.7TB — only the
    block-sparse per-device path makes this executable at all here."""
    from alphafold2_tpu.ops.attention import AxialAttention
    from alphafold2_tpu.ops.sparse import BlockSparseConfig
    from alphafold2_tpu.parallel.sharding import use_mesh

    n = 768
    cfg = BlockSparseConfig(
        block_size=128, num_local_blocks=2, num_global_blocks=1,
        num_random_blocks=0,
    )
    mod = AxialAttention(
        dim=8, heads=1, dim_head=8, sparse_attn=True, seq_len=n,
        sparse_config=cfg, sparse_use_pallas=False, grid_parallel=True,
    )
    x = jax.random.normal(jax.random.key(9), (1, n, n, 8), jnp.float32)
    mesh = make_grid_mesh(1, 2, 4)
    with use_mesh(mesh):
        params = jax.eval_shape(lambda: mod.init(jax.random.key(10), x))
        params = jax.tree.map(
            lambda s: jnp.zeros(s.shape, s.dtype), params
        )
        out = jax.jit(lambda x: mod.apply(params, x))(x)
    assert out.shape == (1, n, n, 8)
    assert np.all(np.isfinite(np.asarray(out)))


@pytest.mark.parametrize("sparse", [False, True])
def test_grid_native_matches_flat_route(sparse):
    """The default grid-native axial route (pointwise projections on the
    grid, no pair-map transpose materialization) computes the same values
    as the flat (B*, n, d) route on the valid region, dense and sparse."""
    from alphafold2_tpu.ops.attention import AxialAttention
    from alphafold2_tpu.ops.sparse import BlockSparseConfig

    n = 8
    kw = dict(dim=16, heads=2, dim_head=8)
    if sparse:
        kw.update(
            sparse_attn=True, seq_len=n, sparse_use_pallas=False,
            sparse_config=BlockSparseConfig(
                block_size=4, num_local_blocks=2, num_global_blocks=1,
                num_random_blocks=0,
            ),
        )
    a = AxialAttention(**kw, grid_native=True)
    b_mod = AxialAttention(**kw, grid_native=False)
    x = jax.random.normal(jax.random.key(11), (2, n, n, 16))
    mask = jnp.ones((2, n, n), bool).at[:, :, -2:].set(False)
    params = a.init(jax.random.key(12), x, mask=mask)

    out_grid = a.apply(params, x, mask=mask)
    out_flat = b_mod.apply(params, x, mask=mask)
    valid = np.asarray(mask)[..., None]
    np.testing.assert_allclose(
        np.asarray(out_grid) * valid, np.asarray(out_flat) * valid,
        atol=2e-5,
    )


def test_grid_mesh_overrides_grid_native_escape():
    """grid_native=False is a flat-route debug escape, but under an active
    grid mesh the sharded pass must still run (the flat route would
    transpose the 2D-sharded pair map — a silent memory cliff)."""
    from alphafold2_tpu.ops.attention import AxialAttention
    from alphafold2_tpu.parallel.sharding import use_mesh

    n = 8
    mod = AxialAttention(dim=16, heads=2, dim_head=8, grid_parallel=True,
                         grid_native=False)
    x = jax.random.normal(jax.random.key(13), (2, n, n, 16))
    params = mod.init(jax.random.key(14), x)
    ref = mod.apply(params, x)
    mesh = make_grid_mesh(2, 2, 2)
    with use_mesh(mesh):
        out = jax.jit(lambda x: mod.apply(params, x))(x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_grid_sparse_unaligned_fails_loudly():
    from alphafold2_tpu.ops.attention import AxialAttention
    from alphafold2_tpu.ops.sparse import BlockSparseConfig
    from alphafold2_tpu.parallel.sharding import use_mesh

    n = 12  # not a multiple of block_size 8
    mod = AxialAttention(
        dim=16, heads=2, dim_head=8, sparse_attn=True, seq_len=16,
        sparse_use_pallas=False, grid_parallel=True,
        sparse_config=BlockSparseConfig(block_size=8),
    )
    x = jax.random.normal(jax.random.key(15), (2, n, n, 16))
    mesh = make_grid_mesh(2, 2, 2)
    with use_mesh(mesh):
        with pytest.raises(ValueError, match="block-aligned"):
            mod.init(jax.random.key(16), x)


@pytest.mark.skipif(
    os.environ.get("AF2TPU_HEAVY") != "1",
    reason="~7 min on CPU; set AF2TPU_HEAVY=1 (verified run: compile 396s, "
    "then 23s/step, finite loss — 2026-07-30)",
)
def test_grid_sparse_768_full_train_step():
    """VERDICT r1 #7 'done' criterion: a FULL 768-crop training step
    (grid_parallel + block-sparse + remat) executes on the 8-virtual-device
    mesh. Dense logits for one axial pass would be ~1.7TB; the sparse
    per-device kernels inside the 2D-sharded passes make this fit."""
    from alphafold2_tpu.config import (
        Config, DataConfig, MeshConfig, ModelConfig, TrainConfig,
    )
    from alphafold2_tpu.data.pipeline import SyntheticDataset
    from alphafold2_tpu.train.loop import (
        build_model, device_put_batch, init_state, make_train_step,
    )

    cfg = Config(
        model=ModelConfig(
            dim=16, depth=1, heads=2, dim_head=8, max_seq_len=1536,
            grid_parallel=True, sparse_self_attn=True, remat=True,
            bfloat16=False,
        ),
        mesh=MeshConfig(data_parallel=1, grid_rows=2, grid_cols=4),
        data=DataConfig(crop_len=768, msa_depth=2, msa_len=32, batch_size=1,
                        min_len_filter=768),
        train=TrainConfig(gradient_accumulate_every=1, warmup_steps=1),
    )
    batch = next(iter(SyntheticDataset(cfg.data, seed=0)))
    model = build_model(cfg)
    state = init_state(cfg, model, batch)
    mesh = make_grid_mesh(1, 2, 4)
    step = make_train_step(model, mesh)
    state, metrics = step(state, device_put_batch(batch, mesh),
                          jax.random.key(1))
    assert np.isfinite(float(metrics["loss"]))


def test_indivisible_axis_raises():
    # N/spr = 4 rows per device, spc = 2 -> fine; but N=6 local rows 3 is
    # not divisible by spc=2 for the transpose
    n = 6
    shape = (B, n, n, HEADS, D)
    q = k = v = jnp.zeros(shape)
    mesh = make_grid_mesh(2, 2, 2)
    with pytest.raises(ValueError, match="must divide"):
        jax.jit(
            lambda q, k, v: grid_axial_attention(q, k, v, mesh=mesh, attend_axis=2)
        )(q, k, v)
