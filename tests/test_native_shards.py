"""Native real-data loader: npz shard chains through the C++ prefetch ring.

Covers the native twin of NpzShardDataset's crop/pad/MSA/label logic:
schema, determinism across worker counts, crop-window provenance, label
parity with the jnp bucketization oracle, CA-only shard handling, and the
length filter. Skipped when libaf2data.so is not built."""

import numpy as np
import pytest

from alphafold2_tpu import constants
from alphafold2_tpu.config import DataConfig
from alphafold2_tpu.data import native

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native library not built (make -C native)"
)


def _write_shards(d, lengths=(30, 18), ca_only_index=None):
    """Distinct token ramps + 1000*i coord offsets identify provenance.
    Small jitter keeps pair distances off exact distogram bin edges (a
    straight 3.8A chain puts many distances exactly on thresholds, where
    float association order flips the bin)."""
    d.mkdir(parents=True, exist_ok=True)
    rng = np.random.default_rng(42)
    for i, n in enumerate(lengths):
        seq = ((np.arange(n) + 7 * i) % 20).astype(np.int32)
        ca = (
            np.cumsum(np.tile([3.8, 0.0, 0.0], (n, 1)), axis=0)
            + 1000.0 * i
            + rng.normal(scale=0.03, size=(n, 3))
        ).astype(np.float32)
        if ca_only_index == i:
            np.savez(d / f"c{i}.npz", seq=seq, coords=ca)
        else:
            bb = np.stack(
                [ca - [1.46, 0, 0], ca, ca + [1.52, 0, 0]], axis=1
            ).astype(np.float32)
            np.savez(d / f"c{i}.npz", seq=seq, coords=bb)


def _cfg(d, **kw):
    base = dict(source="native", data_dir=str(d), crop_len=16, msa_depth=2,
                msa_len=12, batch_size=2, min_len_filter=8,
                max_len_filter=1000)
    base.update(kw)
    return DataConfig(**base)


def test_schema_and_crop_provenance(tmp_path):
    _write_shards(tmp_path / "s")
    with native.NativeShardLoader(_cfg(tmp_path / "s"), seed=0) as ld:
        assert ld.num_chains == 2
        b = next(ld)
    assert b["seq"].shape == (2, 16) and b["seq"].dtype == np.int32
    assert b["msa"].shape == (2, 2, 12)
    assert b["mask"].dtype == bool and b["labels"].shape == (2, 16, 16)
    for i in range(2):
        w = int(b["mask"][i].sum())
        assert w == 16  # both chains (30, 18) >= crop 16: full crops
        # contiguous ramp window proves a real crop of one source chain
        d = np.diff(b["seq"][i, :w].astype(int)) % 20
        assert np.all(d == 1)
        # coords offset identifies which chain; the window start recovered
        # from the x-ramp must reproduce the first cropped token
        chain = int(b["coords"][i, 0, 0] >= 500)
        start = int(round((b["coords"][i, 0, 0] - 1000 * chain) / 3.8)) - 1
        assert b["seq"][i, 0] == (start + 7 * chain) % 20
        # MSA mostly agrees with the cropped sequence (mutation ~0.15)
        ml = min(12, w)
        agree = (b["msa"][i, :, :ml] == b["seq"][i, None, :ml]).mean()
        assert agree > 0.6
        assert b["msa_mask"][i, :, :ml].all()
        assert not b["msa_mask"][i, :, ml:].any()


def test_short_chain_pad_path(tmp_path):
    # a chain SHORTER than the crop exercises fill_from_chains' padding:
    # pad tokens, zero coords/backbone, clamped MSA length, masked labels
    _write_shards(tmp_path / "s", lengths=(12,))
    with native.NativeShardLoader(_cfg(tmp_path / "s"), seed=4) as ld:
        b = next(ld)
    for i in range(2):
        assert int(b["mask"][i].sum()) == 12
        assert (b["seq"][i, 12:] == constants.AA_PAD_INDEX).all()
        np.testing.assert_array_equal(b["coords"][i, 12:], 0.0)
        np.testing.assert_array_equal(b["backbone"][i, 36:], 0.0)
        assert b["msa_mask"][i, :, :12].all()
        assert not b["msa_mask"][i, :, 12:].any()
        assert (b["labels"][i, 12:, :] == -100).all()
        assert (b["labels"][i, :, 12:] == -100).all()


def test_labels_match_jnp_oracle(tmp_path):
    from alphafold2_tpu.utils.structure import get_bucketed_distance_matrix

    _write_shards(tmp_path / "s", lengths=(40,))
    with native.NativeShardLoader(_cfg(tmp_path / "s"), seed=3) as ld:
        b = next(ld)
    want = np.asarray(get_bucketed_distance_matrix(b["coords"], b["mask"]))
    mismatch = (b["labels"] != want).mean()
    assert mismatch < 1e-3, f"label mismatch fraction {mismatch}"


def test_stream_deterministic_across_worker_counts(tmp_path):
    _write_shards(tmp_path / "s")
    cfg = _cfg(tmp_path / "s")
    with native.NativeShardLoader(cfg, seed=5, num_workers=1) as a, \
            native.NativeShardLoader(cfg, seed=5, num_workers=3) as c:
        for _ in range(4):
            ba, bc = next(a), next(c)
            for k in ("seq", "msa", "coords", "labels"):
                np.testing.assert_array_equal(ba[k], bc[k])


def test_ca_only_shard_gets_synthesized_backbone(tmp_path):
    _write_shards(tmp_path / "s", lengths=(24,), ca_only_index=0)
    with native.NativeShardLoader(_cfg(tmp_path / "s"), seed=1) as ld:
        b = next(ld)
    w = int(b["mask"][0].sum())
    bb = b["backbone"][0, : w * 3].reshape(w, 3, 3)
    # CA slot of the synthesized backbone is the shard's CA trace
    np.testing.assert_allclose(bb[:, 1], b["coords"][0, :w], atol=1e-5)
    # N/C pseudo-atoms are ~1.5A off the CA
    d = np.linalg.norm(bb[:, 0] - bb[:, 1], axis=-1)
    assert (d > 0.8).all() and (d < 2.5).all()


def test_malformed_shard_fails_loudly(tmp_path):
    # coords rows != seq length must be rejected in Python — the native
    # registry trusts lengths, so silent acceptance would read out of
    # bounds in C++
    d = tmp_path / "bad"
    d.mkdir()
    np.savez(d / "c.npz", seq=np.zeros(50, np.int32),
             coords=np.zeros((40, 3, 3), np.float32))
    with pytest.raises(ValueError, match="coords shape"):
        native.NativeShardLoader(_cfg(d))

    d2 = tmp_path / "bad2"
    d2.mkdir()
    np.savez(d2 / "c.npz", seq=np.zeros(50, np.int32),
             coords=np.zeros((50, 1, 3), np.float32))
    with pytest.raises(ValueError, match="coords shape"):
        native.NativeShardLoader(_cfg(d2))


def test_stored_msa_shards_fall_back_to_numpy_pipeline(tmp_path):
    # the native loader synthesizes MSAs; shards with REAL stored MSAs must
    # not silently lose them — make_dataset routes to the numpy pipeline
    from alphafold2_tpu.data.pipeline import NpzShardDataset, make_dataset

    d = tmp_path / "m"
    d.mkdir()
    n = 24
    np.savez(
        d / "c.npz", seq=np.zeros(n, np.int32),
        coords=np.zeros((n, 3), np.float32),
        msa=np.ones((3, n), np.int32),
    )
    with pytest.warns(UserWarning, match="stored MSAs"):
        ds = make_dataset(_cfg(d), seed=0)
    assert isinstance(ds, NpzShardDataset)
    with pytest.warns(UserWarning, match="stored MSAs"):
        native.NativeShardLoader(_cfg(d)).close()


def test_length_filter_and_make_dataset(tmp_path):
    from alphafold2_tpu.data.pipeline import make_dataset

    _write_shards(tmp_path / "s", lengths=(30, 18))
    cfg = _cfg(tmp_path / "s", min_len_filter=20)  # drops the 18-chain
    ds = make_dataset(cfg, seed=2)
    assert isinstance(ds, native.NativeShardLoader)
    assert ds.num_chains == 1
    with ds:
        b = next(ds)
    assert b["mask"].all()  # only the 30-chain remains; full crops

    with pytest.raises(ValueError, match="length filter"):
        native.NativeShardLoader(_cfg(tmp_path / "s", min_len_filter=500))
