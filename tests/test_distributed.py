"""Multi-host bootstrap tests (single-process versions on the 8-virtual-CPU
runtime): pod mesh construction/layout, host-local -> global batch assembly,
and a train step consuming globally-sharded input."""

import jax
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from alphafold2_tpu.parallel.distributed import global_batch, initialize, pod_mesh
from alphafold2_tpu.parallel.sharding import DATA_AXIS, SEQ_AXIS

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 (virtual) devices"
)


def test_initialize_single_process_noop(monkeypatch):
    monkeypatch.delenv("COORDINATOR_ADDRESS", raising=False)
    assert initialize() is False  # CPU, no coordinator -> nothing to do


def test_pod_mesh_shapes():
    mesh = pod_mesh(4, 2)
    assert mesh.axis_names == (DATA_AXIS, SEQ_AXIS)
    assert mesh.devices.shape == (4, 2)
    # -1 fills dp with the remaining devices
    assert pod_mesh(-1, 2).devices.shape == (4, 2)
    assert pod_mesh().devices.shape == (8, 1)
    with pytest.raises(ValueError):
        pod_mesh(3, 2)


def test_global_batch_assembly_and_step():
    from alphafold2_tpu.config import Config, DataConfig, ModelConfig, TrainConfig
    from alphafold2_tpu.data.pipeline import SyntheticDataset
    from alphafold2_tpu.train.loop import build_model, init_state, make_train_step

    cfg = Config(
        model=ModelConfig(dim=32, depth=1, heads=2, dim_head=16, max_seq_len=64,
                          bfloat16=False),
        data=DataConfig(crop_len=16, msa_depth=2, msa_len=16, batch_size=4,
                        min_len_filter=8),
        train=TrainConfig(gradient_accumulate_every=1, warmup_steps=2),
    )
    mesh = pod_mesh(4, 2)
    batch = next(iter(SyntheticDataset(cfg.data, seed=0)))
    gb = global_batch(batch, mesh)
    for k, v in gb.items():
        assert v.shape == np.asarray(batch[k]).shape
        assert v.sharding == NamedSharding(mesh, P(DATA_AXIS)), k
        assert np.array_equal(np.asarray(v), np.asarray(batch[k])), k

    model = build_model(cfg)
    state = init_state(cfg, model, batch)
    step = make_train_step(model, mesh=mesh)
    state, metrics = step(state, gb, jax.random.key(0))
    assert np.isfinite(float(metrics["loss"]))
