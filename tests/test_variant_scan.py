"""Variant-scan fast lane tests (serve/cache.py FeatureCache +
serve/bucketing.py family detection/affinity + data/pipeline.py delta
featurization + engine ledger).

The load-bearing contract is byte-level parity: a delta-featurized point
mutant (column patching against a cached parent) must be bit-identical to
cold featurization — tolerance zero, pinned via ``tobytes()``. On top of
that: the content-addressed FeatureCache's interning/eviction/refcount
behavior, mutant-family detection (explicit ``parent_id`` hint and
edit-distance-1 discovery), affinity batch formation (same-family requests
jump ahead, the head is never delayed), and the end-to-end featurize-reuse
ledger on a real engine (``hits + misses + delta == dispatched requests``,
with every ``ServeResult`` stamped with its reuse class)."""

import numpy as np
import pytest

from alphafold2_tpu.config import (
    Config,
    DataConfig,
    ModelConfig,
    ServeConfig,
)
from alphafold2_tpu.data.pipeline import (
    featurize_bucketed,
    featurize_bucketed_with_plan,
    featurize_delta,
)
from alphafold2_tpu.observe import EventCounters, Tracer
from alphafold2_tpu.predict import encode_sequence
from alphafold2_tpu.serve import (
    AsyncServeFrontend,
    FamilyTracker,
    FeatureCache,
    ServeEngine,
    ServeRequest,
    ServeResult,
    affinity_take,
    feature_fingerprint,
    feature_key,
    point_mutation,
)


def _tokens(seq):
    return encode_sequence(seq)[0]


def _mutate(seq, pos, to="W"):
    aa = to if seq[pos] != to else "Y"
    return seq[:pos] + aa + seq[pos + 1:]


# ------------------------------------------------- delta featurization parity


def test_featurize_with_plan_matches_plain():
    tokens = _tokens("ACDEFGHIKLMN")
    plain = featurize_bucketed(tokens, 16, 4, seed=3)
    item, plan = featurize_bucketed_with_plan(tokens, 16, 4, seed=3)
    assert sorted(item) == sorted(plain)
    for name in plain:
        assert item[name].tobytes() == plain[name].tobytes()
        assert item[name].dtype == plain[name].dtype
    assert plan["bucket_len"] == 16 and plan["msa_depth"] == 4
    assert plan["seed"] == 3 and np.array_equal(plan["tokens"], tokens)


@pytest.mark.parametrize("positions", [(0,), (5,), (11,), (0, 11), (2, 5, 9)])
def test_delta_featurization_byte_parity(positions):
    parent = "ACDEFGHIKLMN"  # 12 residues in a 16 bucket
    p_item, plan = featurize_bucketed_with_plan(
        _tokens(parent), 16, 4, seed=5
    )
    mutant = parent
    for p in positions:
        mutant = _mutate(mutant, p)
    mut_tokens = _tokens(mutant)
    delta = featurize_delta(p_item, plan, mut_tokens)
    cold = featurize_bucketed(mut_tokens, 16, 4, seed=5)
    assert sorted(delta) == sorted(cold)
    for name in cold:  # tolerance ZERO: the fast lane may not drift a bit
        assert delta[name].tobytes() == cold[name].tobytes(), name
        assert delta[name].shape == cold[name].shape
        assert delta[name].dtype == cold[name].dtype


def test_delta_parity_with_short_msa_rows():
    # msa_len < L: a mutation past the MSA's effective length touches only
    # the primary sequence, and the column patch must not index past it
    parent = "ACDEFGHIKLMN"
    p_item, plan = featurize_bucketed_with_plan(
        _tokens(parent), 16, 3, seed=9, msa_len=8
    )
    for pos in (3, 10):  # one inside the MSA window, one beyond it
        mutant = _mutate(parent, pos)
        delta = featurize_delta(p_item, plan, _tokens(mutant))
        cold = featurize_bucketed(_tokens(mutant), 16, 3, seed=9, msa_len=8)
        for name in cold:
            assert delta[name].tobytes() == cold[name].tobytes(), name


def test_delta_chains_through_a_mutant():
    # a delta-featurized mutant inherits the parent's plan (with its own
    # tokens) and must itself be a byte-exact delta parent — scan chains
    # survive the original parent aging out of the cache
    parent = "MKVLITHDSAGE"
    p_item, p_plan = featurize_bucketed_with_plan(
        _tokens(parent), 16, 4, seed=2
    )
    m1 = _mutate(parent, 4)
    m1_item = featurize_delta(p_item, p_plan, _tokens(m1))
    m1_plan = dict(p_plan)
    m1_plan["tokens"] = _tokens(m1)
    m2 = _mutate(m1, 9)
    via_chain = featurize_delta(m1_item, m1_plan, _tokens(m2))
    cold = featurize_bucketed(_tokens(m2), 16, 4, seed=2)
    for name in cold:
        assert via_chain[name].tobytes() == cold[name].tobytes(), name


def test_delta_rejects_length_mismatch():
    p_item, plan = featurize_bucketed_with_plan(_tokens("ACDEFG"), 8, 2)
    with pytest.raises(ValueError, match="equal lengths"):
        featurize_delta(p_item, plan, _tokens("ACDEFGH"))


# ---------------------------------------------------------------- FeatureCache


def _leafy(seed, L=4, shared_seq=None):
    """A small featurized-tree stand-in; ``shared_seq`` lets two items
    carry byte-identical seq/mask leaves (the cross-seed intern case)."""
    rng = np.random.default_rng(seed)
    seq = (shared_seq if shared_seq is not None
           else rng.integers(0, 20, L).astype(np.int32))
    return {
        "seq": np.array(seq, np.int32),
        "mask": np.ones(L, bool),
        "msa": rng.integers(0, 20, (2, L)).astype(np.int32),
    }


def test_feature_key_ignores_request_metadata():
    # priority/deadline/parent_id/trace never reach the key: requests
    # differing only in metadata share the featurized entry
    assert feature_key("ACDEFG", 8, 2, 0) == ("ACDEFG", 8, 2, 0)


def test_feature_fingerprint_is_content_addressed():
    a, b = _leafy(1), _leafy(1)
    assert a["seq"] is not b["seq"]
    assert feature_fingerprint(a) == feature_fingerprint(b)
    c = _leafy(2)
    assert feature_fingerprint(a) != feature_fingerprint(c)


def test_feature_cache_roundtrip_freeze_and_interning():
    fc = FeatureCache(8)
    k1 = feature_key("AAAA", 8, 2, 0)
    k2 = feature_key("AAAA", 8, 2, 1)  # different seed, same seq/mask bytes
    shared = np.arange(4, dtype=np.int32)
    i1 = fc.put(k1, _leafy(10, shared_seq=shared), plan={"tokens": shared})
    assert fc.lookup(feature_key("CCCC", 8, 2, 0)) is None  # miss counted
    found = fc.lookup(k1)
    assert found is not None and found[0]["seq"] is i1["seq"]
    i2 = fc.put(k2, _leafy(11, shared_seq=shared))
    # seed-independent leaves intern to ONE array across seeds
    assert i2["seq"] is i1["seq"]
    stats = fc.stats()
    assert stats["leaf_dedup_hits"] >= 1
    assert stats["unique_leaves"] < 6  # 2 entries x 3 leaves, seq+mask shared
    assert stats["hits"] == 1 and stats["misses"] == 1
    # cached arrays are frozen: an in-place edit fails loudly
    with pytest.raises(ValueError):
        i1["seq"][0] = 99


def test_feature_cache_first_put_wins_on_race():
    fc = FeatureCache(4)
    k = feature_key("ACDE", 8, 2, 0)
    first = fc.put(k, _leafy(1))
    second = fc.put(k, _leafy(1))  # racing featurizer: same content
    assert second["seq"] is first["seq"]
    assert len(fc) == 1


def test_feature_cache_eviction_decrefs_interned_leaves():
    fc = FeatureCache(1)
    fc.put(feature_key("AAAA", 8, 2, 0), _leafy(1), plan={"p": 1})
    assert fc.stats()["unique_leaves"] == 3
    fc.put(feature_key("CCCC", 8, 2, 0), _leafy(2), plan={"p": 2})
    assert len(fc) == 1
    # the evicted entry's leaves were decref'd away, not leaked
    assert fc.stats()["unique_leaves"] == 3
    assert fc.lookup(feature_key("AAAA", 8, 2, 0)) is None
    # the shape index followed the eviction: only the survivor remains
    parents = fc.delta_parent(8, 2, 0, 4)
    assert [p[1]["p"] for p in parents] == [2]


def test_feature_cache_delta_parent_window():
    fc = FeatureCache(64)
    n = FeatureCache.DELTA_SCAN + 3
    for i in range(n):
        fc.put(feature_key(f"SEQ{i:04d}", 8, 2, 0), _leafy(i),
               plan={"i": i} if i % 2 == 0 else None)
    parents = fc.delta_parent(8, 2, 0, 7)
    # bounded scan, most recent first, plan-carrying entries only
    assert len(parents) <= FeatureCache.DELTA_SCAN
    idx = [p[1]["i"] for p in parents]
    assert idx == sorted(idx, reverse=True)
    assert fc.delta_parent(16, 2, 0, 7) == []  # other shapes unseen


def test_feature_cache_capacity_zero_is_passthrough():
    fc = FeatureCache(0)
    item = _leafy(1)
    assert fc.put(feature_key("AAAA", 8, 2, 0), item) is item
    assert len(fc) == 0
    assert fc.lookup(feature_key("AAAA", 8, 2, 0)) is None


# ----------------------------------------------- family detection + affinity


def test_point_mutation_detection():
    assert point_mutation("ACDEFG", "ACDEFW") == 5
    assert point_mutation("WCDEFG", "ACDEFG") == 0
    assert point_mutation("ACDEFG", "ACDEFG") is None  # identical
    assert point_mutation("ACDEFG", "ACDEF") is None  # length mismatch
    assert point_mutation("ACDEFG", "WCDEFW") is None  # two substitutions


def test_family_tracker_hint_wins():
    t = FamilyTracker()
    assert t.observe("AAAAAA", parent_id="scan7") == "hint:scan7"
    assert t.observe("AAAAAC", parent_id="scan7") == "hint:scan7"


def test_family_tracker_edit_distance_discovery():
    t = FamilyTracker()
    assert t.observe("ACDEFG") is None  # unmatched: singleton start
    assert t.observe("ACDEFG") is None  # exact repeat of a singleton
    label = t.observe("ACDEFW")  # point mutant: inherits the family
    assert label == "ACDEFG"
    assert t.observe("ACDEFY") == "ACDEFG"  # sibling joins the same family
    assert t.observe("ACDEFW") == "ACDEFG"  # exact repeat of a member
    assert t.observe("MKVLIT") is None  # stranger stays regular traffic


def test_family_tracker_window_is_bounded():
    t = FamilyTracker(window=2)
    t.observe("ACDEFG")
    t.observe("MKVLIT")
    t.observe("WWWWWW")  # pushes ACDEFG out of the window
    assert t.observe("ACDEFW") is None  # parent forgotten: new singleton


class _P:
    def __init__(self, name, family=None):
        self.name = name
        self.family = family


def test_affinity_take_packs_family_and_backfills():
    q = [_P("f1", "fam"), _P("s1"), _P("s2"), _P("f2", "fam"),
         _P("f3", "fam")]
    take = affinity_take(q, 3)
    assert [p.name for p in take] == ["f1", "f2", "f3"]
    # family smaller than the batch: leftover slots backfill queue order
    q2 = [_P("f1", "fam"), _P("s1"), _P("f2", "fam"), _P("s2")]
    assert [p.name for p in affinity_take(q2, 3)] == ["f1", "f2", "s1"]


def test_affinity_take_head_without_family_keeps_queue_order():
    q = [_P("s1"), _P("f1", "fam"), _P("f2", "fam")]
    assert [p.name for p in affinity_take(q, 2)] == ["s1", "f1"]
    assert affinity_take([], 4) == []
    assert affinity_take(q, 0) == []


# -------------------------------------------- scheduler formation (no jax)


def _cfg(buckets=(8, 16), max_batch=2, **serve_kw):
    serve_kw.setdefault("mds_iters", 10)
    return Config(
        model=ModelConfig(dim=32, depth=1, heads=2, dim_head=16,
                          max_seq_len=3 * max(buckets), bfloat16=False),
        data=DataConfig(msa_depth=2),
        serve=ServeConfig(buckets=buckets, max_batch=max_batch, **serve_kw),
    )


class _FakeClock:
    def __init__(self):
        self.now = 100.0

    def __call__(self):
        return self.now

    def advance(self, dt):
        self.now += dt


class _FakeEngine:
    """Dispatch recorder (same stand-in shape as tests/test_scheduler.py)."""

    def __init__(self, cfg):
        self.cfg = cfg
        self.buckets = cfg.serve.buckets
        self.max_batch = cfg.serve.max_batch
        self.mesh_desc = None
        self.counters = EventCounters()
        self.tracer = Tracer(enabled=False)
        self.dispatched = []

    def batch_for(self, bucket):
        return self.max_batch

    def dispatch_batch(self, bucket, reqs):
        self.dispatched.append((bucket, [r.seq for r in reqs]))
        return [
            ServeResult(seq=r.seq, bucket=bucket,
                        atom14=np.zeros((len(r.seq), 14, 3), np.float32),
                        latency_s=1e-3)
            for r in reqs
        ]

    def retry_bucket(self, bucket):
        return None


def _frontend(**serve_kw):
    serve_kw.setdefault("dwell_ms", 50.0)
    eng = _FakeEngine(_cfg(**serve_kw))
    clock = _FakeClock()
    fe = AsyncServeFrontend(eng, clock=clock, start=False)
    return fe, eng, clock


def test_scheduler_affinity_packs_hinted_family():
    fe, eng, clock = _frontend()
    h1 = fe.submit(ServeRequest("AAAAAA", parent_id="scan"))
    hs = fe.submit("MKVLIT")  # stranger between two family members
    h2 = fe.submit(ServeRequest("AAAAAC", parent_id="scan"))
    assert fe.pump() == 1
    # the family member jumped ahead of the stranger into the formation
    assert eng.dispatched == [(8, ["AAAAAA", "AAAAAC"])]
    assert h1.result(0).ok and h2.result(0).ok
    clock.advance(0.051)
    assert fe.pump() == 1  # the stranger still dispatches (dwell expiry)
    assert hs.result(0).ok
    stats = fe.stats()
    assert stats["sched.family_members"] == 2
    assert stats["sched.affinity_batches"] == 1


def test_scheduler_affinity_disabled_keeps_fifo():
    fe, eng, clock = _frontend(affinity_batching=False)
    fe.submit(ServeRequest("AAAAAA", parent_id="scan"))
    fe.submit("MKVLIT")
    fe.submit(ServeRequest("AAAAAC", parent_id="scan"))
    assert fe.pump() == 1
    assert eng.dispatched == [(8, ["AAAAAA", "MKVLIT"])]
    assert "sched.family_members" not in fe.stats()


def test_scheduler_affinity_never_delays_the_head():
    fe, eng, clock = _frontend()
    fe.submit("MKVLIT")  # familyless head of queue
    fe.submit(ServeRequest("AAAAAA", parent_id="scan"))
    fe.submit(ServeRequest("AAAAAC", parent_id="scan"))
    assert fe.pump() >= 1
    # the oldest request rides in the first formation regardless of family
    assert eng.dispatched[0] == (8, ["MKVLIT", "AAAAAA"])


# ------------------------------------------------ real-engine ledger + parity


def _engine_cfg(**serve_kw):
    serve_kw.setdefault("mds_iters", 20)
    serve_kw.setdefault("feature_cache_size", 64)
    return Config(
        model=ModelConfig(dim=32, depth=1, heads=2, dim_head=16,
                          max_seq_len=48, bfloat16=False),
        data=DataConfig(msa_depth=2),
        serve=ServeConfig(buckets=(16,), max_batch=4, **serve_kw),
    )


@pytest.fixture(scope="module")
def scan_engine():
    return ServeEngine(_engine_cfg())


def test_engine_ledger_accounts_every_request(scan_engine):
    eng = scan_engine
    parent = "ACDEFGHIKLMN"
    muts = [_mutate(parent, p) for p in (0, 3, 7, 11)]
    reqs = [ServeRequest(parent)] + [
        ServeRequest(m, parent_id="fam0") for m in muts
    ]
    before = eng.counters.snapshot()
    results = eng.predict_many(reqs)
    after = eng.counters.snapshot()

    def d(name):
        return after.get(name, 0) - before.get(name, 0)

    hits, misses, delta = (d("serve.feat_hits"), d("serve.feat_misses"),
                           d("serve.feat_delta"))
    # the ledger sums to the dispatched-request count, no request uncounted
    assert hits + misses + delta == len(reqs)
    assert misses == 1 and delta == len(muts)
    assert all(r.ok for r in results)
    assert [r.feat_reuse for r in results] == ["miss"] + ["delta"] * len(muts)
    # an exact repeat of the parent is a derivation-key hit
    again = eng.predict_many([ServeRequest(parent)])[0]
    assert again.feat_reuse == "hit"
    assert eng.counters.get("serve.feat_hits") >= 1


def test_delta_served_result_matches_cold_engine(scan_engine):
    # end-to-end parity: a structure served through the delta fast lane is
    # byte-identical to the same request on an engine with the lane off
    parent = "MKVLITHDSAGE"
    mutant = _mutate(parent, 6)
    warm = scan_engine.predict_many(
        [ServeRequest(parent), ServeRequest(mutant, parent_id="fam1")]
    )
    assert warm[1].feat_reuse == "delta"
    cold_eng = ServeEngine(_engine_cfg(feature_cache_size=0,
                                       delta_featurize=False))
    cold = cold_eng.predict_many([ServeRequest(mutant)])[0]
    assert cold.feat_reuse == "miss"
    assert np.array_equal(warm[1].atom14, cold.atom14)
