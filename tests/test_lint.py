"""Rule-by-rule tests of the graph-hygiene AST linter (analysis/lint.py):
for every rule, a bad snippet must produce exactly that finding and its
noqa'd twin must be clean; jit-context detection must see decorators,
module-level jit(...) calls (including methods) and lax control-flow
bodies; and the repo itself must lint clean — the acceptance bar the CI
af2-lint job enforces."""

import json
import os
import subprocess
import sys
import textwrap

from alphafold2_tpu.analysis import lint

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def rules_of(src: str) -> list:
    return [f.rule for f in lint.lint_source(textwrap.dedent(src))]


# ------------------------------------------------------------ rule by rule


def test_traced_if_flagged_and_noqa_clean():
    bad = """
    import jax

    @jax.jit
    def f(x):
        if x > 0:
            return x
        return -x
    """
    assert rules_of(bad) == ["AF2L001"]
    assert rules_of(bad.replace("if x > 0:", "if x > 0:  # af2: noqa[AF2L001]")) == []


def test_traced_while_and_bare_name_truthiness():
    src = """
    import jax

    @jax.jit
    def f(x):
        while x:
            x = x - 1
        return x
    """
    assert rules_of(src) == ["AF2L001"]


def test_none_and_membership_checks_are_exempt():
    src = """
    import jax

    @jax.jit
    def f(x, msa):
        if msa is None:
            return x
        if "k" in {"k": 1}:
            return x
        return x + msa
    """
    assert rules_of(src) == []


def test_host_sync_item_float_asarray_device_get():
    src = """
    import jax
    import numpy as np

    @jax.jit
    def f(x):
        a = x.item()
        b = float(x)
        c = np.asarray(x)
        d = jax.device_get(x)
        return a + b + c + d
    """
    assert rules_of(src) == ["AF2L002"] * 4


def test_float_on_nontraced_value_is_clean():
    src = """
    import jax

    @jax.jit
    def f(x, n):
        scale = float(3)
        return x * scale
    """
    assert rules_of(src) == []


def test_wallclock_and_rng_under_trace():
    src = """
    import time
    import random
    import numpy as np
    import jax

    @jax.jit
    def f(x):
        t = time.perf_counter()
        r = random.random()
        s = np.random.normal()
        return x * t * r * s
    """
    assert rules_of(src) == ["AF2L003", "AF2L004", "AF2L004"]


def test_jax_random_is_not_flagged():
    src = """
    import jax

    @jax.jit
    def f(x, key):
        return x + jax.random.normal(key, x.shape)
    """
    assert rules_of(src) == []


def test_mutable_default_and_bare_except_outside_jit():
    src = """
    def f(x, cache={}):
        try:
            return cache[x]
        except:
            return None
    """
    assert rules_of(src) == ["AF2L005", "AF2L006"]


def test_static_argnames_exempts_param():
    src = """
    from functools import partial
    import jax

    @partial(jax.jit, static_argnames=("n",))
    def f(x, n):
        if n > 2:
            return x * n
        for _ in range(n):
            x = x + 1
        return x
    """
    assert rules_of(src) == []


def test_range_over_traced_param_needs_static():
    src = """
    import jax

    @jax.jit
    def f(x, n):
        for _ in range(n):
            x = x + 1
        return x
    """
    assert rules_of(src) == ["AF2L007"]


def test_print_and_side_effects_under_trace():
    src = """
    import jax

    @jax.jit
    def f(self, x):
        print("tracing")
        self.counters.bump("traces")
        return x
    """
    assert rules_of(src) == ["AF2L008", "AF2L009"]


# ------------------------------------------------------ context detection


def test_module_level_jit_call_marks_function():
    src = """
    import jax

    def step(state, batch):
        if batch > 0:
            return state
        return state

    train = jax.jit(step, donate_argnums=0)
    """
    assert rules_of(src) == ["AF2L001"]


def test_jit_on_method_marks_method():
    src = """
    import jax

    class Engine:
        def _fwd(self, params, seq):
            seq.item()
            return params

        def compile(self):
            return jax.jit(self._fwd)
    """
    assert rules_of(src) == ["AF2L002"]


def test_static_argnums_resolved_against_positional_args():
    src = """
    import jax

    def f(x, n):
        for _ in range(n):
            x = x + 1
        return x

    g = jax.jit(f, static_argnums=(1,))
    """
    assert rules_of(src) == []


def test_nested_function_inherits_jit_context():
    src = """
    import jax

    @jax.jit
    def outer(x):
        def inner(y):
            return y.item()
        return inner(x)
    """
    assert rules_of(src) == ["AF2L002"]


def test_lax_scan_body_is_traced_context():
    src = """
    import jax

    def model(xs):
        def body(carry, x):
            if x > 0:
                carry = carry + x
            return carry, x
        return jax.lax.scan(body, 0.0, xs)
    """
    assert rules_of(src) == ["AF2L001"]


def test_unjitted_function_is_left_alone():
    src = """
    import time

    def host_loop(x):
        t = time.time()
        print(x)
        return x.item() + t
    """
    assert rules_of(src) == []


def test_blanket_noqa_suppresses_all_rules():
    src = """
    import jax

    @jax.jit
    def f(x):
        return x.item()  # af2: noqa
    """
    assert rules_of(src) == []


# ------------------------------------------- thread-safety (AF2L010-012)


def test_blocking_call_under_lock_flagged():
    src = """
    import threading
    import time

    class Q:
        def __init__(self):
            self._lock = threading.Lock()
            self._items = []

        def add(self, x):
            with self._lock:
                self._items.append(x)
                time.sleep(0.1)
    """
    assert rules_of(src) == ["AF2L010"]
    assert rules_of(src.replace(
        "time.sleep(0.1)", "time.sleep(0.1)  # af2: noqa[AF2L010]"
    )) == []


def test_condition_wait_under_lock_is_not_blocking():
    """Waiting on the lock's own condition RELEASES it — the one
    blocking-looking call that is the correct pattern."""
    src = """
    import threading

    class Q:
        def __init__(self):
            self._cv = threading.Condition()
            self._items = []

        def get(self):
            with self._cv:
                while not self._items:
                    self._cv.wait()
                return self._items.pop()
    """
    assert rules_of(src) == []


def test_guarded_state_mutated_outside_lock():
    src = """
    import threading

    class Q:
        def __init__(self):
            self._lock = threading.Lock()
            self._items = []

        def add(self, x):
            with self._lock:
                self._items.append(x)

        def drop(self):
            self._items.pop()
    """
    assert rules_of(src) == ["AF2L011"]
    # __init__ assignments never fire (the snippet's self._items = [] is
    # silent); the *_locked suffix documents "caller holds the lock"
    assert rules_of(src.replace("def drop", "def drop_locked")) == []
    assert rules_of(src.replace(
        "self._items.pop()", "self._items.pop()  # af2: noqa[AF2L011]"
    )) == []


def test_locked_suffix_method_assumes_lock_held():
    """The *_locked convention cuts both ways: its body is a critical
    section, so blocking calls inside it fire AF2L010."""
    src = """
    import threading
    import time

    class Q:
        def __init__(self):
            self._lock = threading.Lock()

        def _flush_locked(self):
            time.sleep(0.5)
    """
    assert rules_of(src) == ["AF2L010"]


def test_host_sync_in_thread_body_flagged():
    src = """
    import threading
    import jax

    class W:
        def start(self):
            self._t = threading.Thread(target=self._run, daemon=True)
            self._t.start()

        def _run(self):
            jax.device_get(self.buf)
    """
    assert rules_of(src) == ["AF2L012"]


def test_host_sync_outside_thread_body_is_fine():
    src = """
    import jax

    class W:
        def fetch(self):
            return jax.device_get(self.buf)
    """
    assert rules_of(src) == []


def test_serve_layer_threadsafety_clean():
    """The satellite's acceptance bar, pinned per file: the scheduler and
    the engine — the two lock-heavy serve modules — carry zero findings."""
    for rel in ("serve/scheduler.py", "serve/engine.py"):
        path = os.path.join(REPO, "alphafold2_tpu", rel)
        findings = lint.lint_file(path)
        assert findings == [], "\n".join(f.format() for f in findings)


# ----------------------------------------------------------- repo + CLI


def test_package_lints_clean():
    """The acceptance bar: the shipped package has no findings (genuine
    violations fixed, intentional ones suppressed with a reasoned noqa)."""
    findings = lint.lint_paths([os.path.join(REPO, "alphafold2_tpu")])
    assert findings == [], "\n".join(f.format() for f in findings)


def test_cli_exits_1_with_rule_and_location(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(
        "import jax\n\n@jax.jit\ndef f(x):\n    return x.item()\n"
    )
    out_json = tmp_path / "findings.json"
    proc = subprocess.run(
        [
            sys.executable, os.path.join(REPO, "scripts", "af2_lint.py"),
            "--json", str(out_json), str(bad),
        ],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 1
    assert "AF2L002" in proc.stdout
    assert f"{bad}:5:" in proc.stdout  # file:line anchoring
    doc = json.loads(out_json.read_text())
    assert doc["counts"]["error"] == 1
    assert doc["findings"][0]["rule"] == "AF2L002"


def test_cli_exits_0_on_clean_file(tmp_path):
    good = tmp_path / "good.py"
    good.write_text("import jax\n\n@jax.jit\ndef f(x):\n    return x + 1\n")
    proc = subprocess.run(
        [
            sys.executable, os.path.join(REPO, "scripts", "af2_lint.py"),
            str(good),
        ],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_syntax_error_is_a_finding_not_a_crash(tmp_path):
    broken = tmp_path / "broken.py"
    broken.write_text("def f(:\n")
    findings = lint.lint_file(str(broken))
    assert [f.rule for f in findings] == ["AF2L000"]
