"""Layer-5 concurrency auditor + knob registry tests.

Three tiers:

1. Synthetic fixtures (fast, jax-free): one tmp-file source per AF2C
   rule proving the rule fires on the defect and stays silent on the
   idiomatic fix, plus exemptions (``__init__``, ``*_locked``, noqa,
   gated-defect modes) and the contract roundtrip
   (compute -> write -> check: pass / drift / stale / missing).
2. Repo-level (fast): the live tree audits clean, the committed
   ``concurrency_contracts.json`` matches a fresh computation, the knob
   registry is clean, and the seeded ``AF2TPU_AUDIT_INVERT_LOCKS``
   control flips the audit to a named AF2C001 cycle without touching
   the contracts.
3. Slow tier: subprocess gate rc semantics, and a LockWitness-threaded
   run through the real dispatcher asserting every runtime lock edge is
   present in the static graph (model vs reality).
"""

import json
import os
import subprocess
import sys
import textwrap

import pytest

from alphafold2_tpu.analysis import concurrency, knobs
from alphafold2_tpu.analysis.concurrency import (
    RepoModel,
    build_model,
    check_against,
    compute_contracts,
    diff_contracts,
    write_contracts,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def scan(tmp_path, source, gated="env"):
    f = tmp_path / "fixture.py"
    f.write_text(textwrap.dedent(source))
    return RepoModel().scan_paths([str(f)], gated=gated)


def rules_of(model):
    return sorted(f.rule for f in model.findings())


# ------------------------------------------------- AF2C001: lock ordering


CYCLE_SRC = """
    import threading

    class A:
        def __init__(self):
            self._lock = threading.Lock()

        def fwd(self, b: "B"):
            with self._lock:
                with b._lock:
                    pass

    class B:
        def __init__(self):
            self._lock = threading.Lock()

        def rev(self, a: "A"):
            with self._lock:
                with a._lock:
                    pass
"""


def test_af2c001_cycle_with_two_witness_paths(tmp_path):
    model = scan(tmp_path, CYCLE_SRC)
    found = [f for f in model.findings() if f.rule == "AF2C001"]
    assert len(found) == 1
    msg = found[0].message
    # both directions of the inversion are named with their sites
    assert "A._lock -> B._lock" in msg
    assert "B._lock -> A._lock" in msg
    assert "acquired at" in msg


def test_af2c001_consistent_order_is_clean(tmp_path):
    model = scan(tmp_path, """
        import threading

        class A:
            def __init__(self):
                self._lock = threading.Lock()

            def fwd(self, b: "B"):
                with self._lock:
                    with b._lock:
                        pass

            def fwd2(self, b: "B"):
                with self._lock:
                    with b._lock:
                        pass

        class B:
            def __init__(self):
                self._lock = threading.Lock()
    """)
    assert ("A._lock", "B._lock") in model.edges
    assert not model.cycles()
    assert "AF2C001" not in rules_of(model)


def test_af2c001_cycle_through_call_closure(tmp_path):
    # A.outer holds A._lock and calls B.helper, which acquires B._lock;
    # B.back holds B._lock and calls A.helper acquiring A._lock — the
    # cycle crosses method calls, not just literal nesting.
    model = scan(tmp_path, """
        import threading

        class A:
            def __init__(self):
                self._lock = threading.Lock()

            def outer(self, b: "B"):
                with self._lock:
                    b.helper()

            def helper(self):
                with self._lock:
                    pass

        class B:
            def __init__(self):
                self._lock = threading.Lock()

            def helper(self):
                with self._lock:
                    pass

            def back(self, a: "A"):
                with self._lock:
                    a.helper()
    """)
    assert ("A._lock", "B._lock") in model.edges
    assert ("B._lock", "A._lock") in model.edges
    assert "AF2C001" in rules_of(model)


def test_af2c001_plain_lock_self_deadlock(tmp_path):
    model = scan(tmp_path, """
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()

            def boom(self):
                with self._lock:
                    with self._lock:
                        pass
    """)
    found = [f for f in model.findings() if f.rule == "AF2C001"]
    assert len(found) == 1
    assert "self-deadlock" in found[0].message


def test_af2c001_rlock_reentry_is_clean(tmp_path):
    model = scan(tmp_path, """
        import threading

        class C:
            def __init__(self):
                self._lock = threading.RLock()

            def fine(self):
                with self._lock:
                    with self._lock:
                        pass
    """)
    assert rules_of(model) == []


def test_acquire_release_pairing_tracks_held_stack(tmp_path):
    # after release the lock is no longer held, so no A->B edge forms
    model = scan(tmp_path, """
        import threading

        class A:
            def __init__(self):
                self._lock = threading.Lock()
                self._other = threading.Lock()

            def seq(self):
                self._lock.acquire()
                self._lock.release()
                self._other.acquire()
                self._other.release()
    """)
    assert model.edges == {}

    model = scan(tmp_path, """
        import threading

        class A:
            def __init__(self):
                self._lock = threading.Lock()
                self._other = threading.Lock()

            def nested(self):
                self._lock.acquire()
                self._other.acquire()
                self._other.release()
                self._lock.release()
    """)
    assert ("A._lock", "A._other") in model.edges


# --------------------------------------- AF2C002/003/004: guard contracts


GUARDED_SRC = """
    import threading

    class C:
        def __init__(self):
            self._lock = threading.Lock()
            self._x = 0

        def w1(self):
            with self._lock:
                self._x = 1

        def w2(self):
            with self._lock:
                self._x = 2

        def bad(self):
            self._x = 3
"""


def test_af2c002_unguarded_write(tmp_path):
    model = scan(tmp_path, GUARDED_SRC)
    # guard values are bare attr names; printing/contract layers qualify
    assert model.guards.get("C", {}).get("_x") == "_lock"
    found = [f for f in model.findings() if f.rule == "AF2C002"]
    assert len(found) == 1
    assert "C._x" in found[0].message


def test_init_writes_are_exempt(tmp_path):
    # __init__ is the only unlocked writer -> no contract pressure and
    # no finding, even though it never takes the lock
    model = scan(tmp_path, """
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._x = 0
                self._x = 1

            def w(self):
                with self._lock:
                    self._x = 2
    """)
    assert model.guards.get("C", {}).get("_x") == "_lock"
    assert rules_of(model) == []


def test_locked_suffix_methods_count_as_guarded(tmp_path):
    model = scan(tmp_path, """
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._x = 0

            def w(self):
                with self._lock:
                    self._x = 1

            def _bump_locked(self):
                self._x += 1
    """)
    assert model.guards.get("C", {}).get("_x") == "_lock"
    assert rules_of(model) == []


def test_private_helper_called_only_under_lock_inherits_it(tmp_path):
    # _flush has no lock syntax of its own, but its only call site holds
    # C._lock — the entry-held fixpoint promotes its writes to locked
    model = scan(tmp_path, """
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._buf = []

            def add(self, item):
                with self._lock:
                    self._buf.append(item)
                    self._flush()

            def _flush(self):
                self._buf = []
    """)
    assert model.guards.get("C", {}).get("_buf") == "_lock"
    assert rules_of(model) == []


def test_af2c003_mixed_guard(tmp_path):
    model = scan(tmp_path, """
        import threading

        class C:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()
                self._x = 0

            def w1(self):
                with self._a:
                    self._x = 1

            def w2(self):
                with self._a:
                    self._x = 2

            def odd(self):
                with self._b:
                    self._x = 3
    """)
    found = [f for f in model.findings() if f.rule == "AF2C003"]
    assert len(found) == 1
    assert "C._a" in found[0].message and "written under _b" in found[0].message


def test_af2c004_unlocked_iteration(tmp_path):
    model = scan(tmp_path, """
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._items = {}

            def put(self, k, v):
                with self._lock:
                    self._items[k] = v

            def wipe(self):
                with self._lock:
                    self._items.clear()

            def snapshot(self):
                return list(self._items.values())

            def peek(self, k):
                return self._items.get(k)
    """)
    found = [f for f in model.findings() if f.rule == "AF2C004"]
    # .values() iteration flagged; single-key .get() is GIL-atomic, clean
    assert len(found) == 1
    assert found[0].severity == "warning"
    assert "C._items" in found[0].message


def test_noqa_suppresses_a_finding(tmp_path):
    model = scan(tmp_path, GUARDED_SRC.replace(
        "self._x = 3", "self._x = 3  # af2: noqa[AF2C002]"
    ))
    assert "AF2C002" not in rules_of(model)


# --------------------------------------------- AF2C005-008: lifecycles


def test_af2c005_thread_without_daemon_or_join(tmp_path):
    model = scan(tmp_path, """
        import threading

        def leak():
            t = threading.Thread(target=print)
            t.start()
    """)
    assert rules_of(model) == ["AF2C005"]


def test_af2c005_daemon_and_joined_variants_are_clean(tmp_path):
    model = scan(tmp_path, """
        import threading

        def daemonized():
            t = threading.Thread(target=print, daemon=True)
            t.start()

        def joined():
            t = threading.Thread(target=print)
            t.start()
            t.join()

        class Worker:
            def start(self):
                self._t = threading.Thread(target=print)
                self._t.start()

            def stop(self):
                self._t.join()
    """)
    assert rules_of(model) == []


def test_af2c006_unbounded_queue_in_threaded_class(tmp_path):
    model = scan(tmp_path, """
        import queue
        import threading
        from collections import deque

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._q = queue.Queue()
                self._d = deque()
                self._ok_q = queue.Queue(maxsize=64)
                self._ok_d = deque(maxlen=64)
    """)
    found = [f for f in model.findings() if f.rule == "AF2C006"]
    assert sorted(f.message.split()[0] for f in found) == ["C._d", "C._q"]
    assert all(f.severity == "warning" for f in found)


def test_af2c006_silent_without_threading_evidence(tmp_path):
    # same queues in a lockless, threadless class: not a concurrency bug
    model = scan(tmp_path, """
        import queue

        class C:
            def __init__(self):
                self._q = queue.Queue()
    """)
    assert rules_of(model) == []


def test_af2c007_naked_wait(tmp_path):
    model = scan(tmp_path, """
        import threading

        class C:
            def __init__(self):
                self._cv = threading.Condition()
                self._ready = False

            def bad(self):
                with self._cv:
                    self._cv.wait()

            def good(self):
                with self._cv:
                    while not self._ready:
                        self._cv.wait()

            def also_good(self):
                with self._cv:
                    self._cv.wait_for(lambda: self._ready)
    """)
    found = [f for f in model.findings() if f.rule == "AF2C007"]
    assert len(found) == 1
    assert found[0].line < 12  # only the `bad` wait


def test_af2c008_callbacks_under_lock(tmp_path):
    model = scan(tmp_path, """
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._callbacks = []

            def bad(self, ev):
                with self._lock:
                    for cb in self._callbacks:
                        cb(ev)

            def good(self, ev):
                with self._lock:
                    snapshot = list(self._callbacks)
                for cb in snapshot:
                    cb(ev)
    """)
    found = [f for f in model.findings() if f.rule == "AF2C008"]
    assert len(found) == 1
    assert "C._lock" in found[0].message


def test_af2c000_unparseable_source(tmp_path):
    f = tmp_path / "broken.py"
    f.write_text("def nope(:\n")
    model = RepoModel().scan_paths([str(f)])
    assert rules_of(model) == ["AF2C000"]


# ------------------------------------------------ gated-defect machinery


def _gated_fixture_source():
    return textwrap.dedent("""
        import threading

        class A:
            def __init__(self):
                self._lock = threading.Lock()

            def fwd(self, b: "B"):
                with self._lock:
                    with b._lock:
                        pass

        class B:
            def __init__(self):
                self._lock = threading.Lock()


        def seeded(a: A, b: B):  # af2: gated-defect[AF2C_TEST_GATE]
            with b._lock:
                with a._lock:
                    pass
    """)


def test_gated_defect_modes(tmp_path, monkeypatch):
    f = tmp_path / "gated.py"
    f.write_text(_gated_fixture_source())

    # env unset: the seeded inversion is invisible
    monkeypatch.delenv("AF2C_TEST_GATE", raising=False)
    model = RepoModel().scan_paths([str(f)], gated="env")
    assert ("B._lock", "A._lock") not in model.edges

    # env set truthy: the audit sees the cycle
    monkeypatch.setenv("AF2C_TEST_GATE", "1")
    model = RepoModel().scan_paths([str(f)], gated="env")
    assert ("B._lock", "A._lock") in model.edges
    assert "AF2C001" in rules_of(model)

    # "none": always excluded even with the env set (contract path)
    model = RepoModel().scan_paths([str(f)], gated="none")
    assert ("B._lock", "A._lock") not in model.edges

    # "all": always included even with the env unset (test path)
    monkeypatch.delenv("AF2C_TEST_GATE", raising=False)
    model = RepoModel().scan_paths([str(f)], gated="all")
    assert ("B._lock", "A._lock") in model.edges


def test_contracts_never_contain_gated_defects(tmp_path, monkeypatch):
    f = tmp_path / "gated.py"
    f.write_text(_gated_fixture_source())
    monkeypatch.setenv("AF2C_TEST_GATE", "1")
    model = RepoModel().scan_paths([str(f)], gated="env")
    contracts = compute_contracts(model, paths=[str(f)])
    assert "B._lock -> A._lock" not in contracts["lock_graph"]
    assert "A._lock -> B._lock" in contracts["lock_graph"]


# ------------------------------------------------- contract roundtrip


def test_contract_roundtrip_pass_drift_stale_missing(tmp_path):
    f = tmp_path / "fixture.py"
    f.write_text(textwrap.dedent(GUARDED_SRC))
    model = RepoModel().scan_paths([str(f)])
    contracts = compute_contracts(model, paths=[str(f)])
    baseline = tmp_path / "contracts.json"

    verdict, lines = check_against(str(baseline), contracts)
    assert verdict == "missing-baseline"

    write_contracts(str(baseline), contracts)
    verdict, lines = check_against(str(baseline), contracts)
    assert (verdict, lines) == ("pass", [])

    mutated = json.loads(json.dumps(contracts))
    mutated["guards"]["C"]["_y"] = "C._lock"
    mutated["lock_graph"]["X._a -> X._b"] = "x.py:1 (X.m)"
    diff = diff_contracts(contracts, mutated)
    assert any(d.startswith("lock-graph edge added") for d in diff)
    assert any(d.startswith("guard added") for d in diff)
    verdict, lines = check_against(str(baseline), mutated)
    assert verdict == "drift" and lines

    mutated["format"] = concurrency.FORMAT_VERSION + 1
    verdict, lines = check_against(str(baseline), mutated)
    assert verdict == "stale-baseline"


def test_cli_check_and_exit_codes(tmp_path, capsys):
    # a clean fixture: guarded writes only, no findings
    f = tmp_path / "fixture.py"
    f.write_text(textwrap.dedent("""
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._x = 0

            def w1(self):
                with self._lock:
                    self._x = 1

            def w2(self):
                with self._lock:
                    self._x = 2
    """))
    baseline = tmp_path / "contracts.json"
    assert concurrency.main(
        ["--update", "--baseline", str(baseline), str(f)]
    ) == 0
    assert concurrency.main(["--baseline", str(baseline), str(f)]) == 0
    assert concurrency.main(
        ["--check", "--baseline", str(baseline), str(f)]
    ) == 0
    assert concurrency.main(
        ["--check", "--baseline", str(tmp_path / "nope.json"), str(f)]
    ) == 2
    # drift: mutate the baseline so the live graph no longer matches
    doc = json.loads(baseline.read_text())
    doc["guards"]["C"]["_ghost"] = "_lock"
    baseline.write_text(json.dumps(doc))
    assert concurrency.main(
        ["--check", "--baseline", str(baseline), str(f)]
    ) == 1
    # an audit finding drives rc 1 even when contracts pass
    f.write_text(textwrap.dedent(GUARDED_SRC))
    concurrency.main(["--update", "--baseline", str(baseline), str(f)])
    assert concurrency.main(
        ["--check", "--baseline", str(baseline), str(f)]
    ) == 1
    capsys.readouterr()


# ----------------------------------------------------- repo-level gates


def test_repo_audit_is_clean():
    model = build_model()
    assert model.findings() == []


def test_committed_contracts_match_reality():
    with open(concurrency.DEFAULT_BASELINE) as fh:
        committed = json.load(fh)
    assert committed == compute_contracts()
    # the one real cross-class edge the serve plane holds today
    assert any(
        e.startswith("AsyncServeFrontend._lock -> PipelineBatch._lock")
        for e in committed["lock_graph"]
    )


def test_inverted_lock_control_fires_af2c001(monkeypatch):
    monkeypatch.setenv("AF2TPU_AUDIT_INVERT_LOCKS", "1")
    model = build_model()
    found = [f for f in model.findings() if f.rule == "AF2C001"]
    assert len(found) == 1
    msg = found[0].message
    assert "PipelineBatch._lock" in msg
    assert "AsyncServeFrontend._lock" in msg
    # the seeded defect never leaks into the contracts
    contracts = compute_contracts(model)
    verdict, _ = check_against(concurrency.DEFAULT_BASELINE, contracts)
    assert verdict == "pass"


@pytest.mark.slow
def test_subprocess_gate_rc_semantics():
    env = dict(os.environ)
    env.pop("AF2TPU_AUDIT_INVERT_LOCKS", None)
    clean = subprocess.run(
        [sys.executable, "-m", "alphafold2_tpu.analysis.concurrency",
         "--check"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=120,
    )
    assert clean.returncode == 0, clean.stdout + clean.stderr

    env["AF2TPU_AUDIT_INVERT_LOCKS"] = "1"
    inverted = subprocess.run(
        [sys.executable, "-m", "alphafold2_tpu.analysis.concurrency"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=120,
    )
    assert inverted.returncode == 1, inverted.stdout + inverted.stderr
    assert "AF2C001" in inverted.stdout
    assert "PipelineBatch._lock" in inverted.stdout


# ------------------------------------------------------- knob registry


def test_repo_knob_audit_is_clean():
    assert knobs.audit() == []


def test_knob_markdown_covers_every_read():
    reads = knobs.collect_env_reads(knobs.default_code_paths())
    assert len(reads) >= 100  # the registry is big and should stay big
    md = knobs.markdown_registry(reads)
    for name in reads:
        assert f"`{name}`" in md


def test_af2k001_undocumented_knob(tmp_path):
    code = tmp_path / "mod.py"
    code.write_text('import os\nX = os.environ.get("AF2TPU_FAKE_KNOB")\n')
    readme = tmp_path / "README.md"
    readme.write_text("nothing here\n")
    cfg = tmp_path / "config.py"
    cfg.write_text("")
    findings = knobs.audit(
        code_paths=[str(code)], liveness_paths=[str(code)],
        readme_path=str(readme), config_path=str(cfg),
    )
    assert [f.rule for f in findings] == ["AF2K001"]
    assert "AF2TPU_FAKE_KNOB" in findings[0].message


def test_af2k002_dead_documented_knob(tmp_path):
    code = tmp_path / "mod.py"
    code.write_text("")
    readme = tmp_path / "README.md"
    readme.write_text("set `AF2TPU_GHOST_KNOB=1` to do nothing\n")
    cfg = tmp_path / "config.py"
    cfg.write_text("")
    findings = knobs.audit(
        code_paths=[str(code)], liveness_paths=[str(code)],
        readme_path=str(readme), config_path=str(cfg),
    )
    assert [f.rule for f in findings] == ["AF2K002"]


def test_prefix_wildcard_keeps_family_alive(tmp_path):
    code = tmp_path / "mod.py"
    code.write_text(
        'import os\n'
        'PREFIX = "AF2TPU_FAM_"\n'
        'vals = {k: v for k, v in os.environ.items()'
        ' if k.startswith(PREFIX)}\n'
    )
    readme = tmp_path / "README.md"
    readme.write_text(
        "`AF2TPU_FAM_` prefix family: `AF2TPU_FAM_ALPHA`, "
        "`AF2TPU_FAM_BETA`\n"
    )
    cfg = tmp_path / "config.py"
    cfg.write_text("")
    findings = knobs.audit(
        code_paths=[str(code)], liveness_paths=[str(code)],
        readme_path=str(readme), config_path=str(cfg),
    )
    assert findings == []


def test_af2k003_and_af2k004_config_fields(tmp_path):
    code = tmp_path / "mod.py"
    code.write_text("def use(c):\n    return c.live_field\n")
    readme = tmp_path / "README.md"
    readme.write_text("")
    cfg = tmp_path / "config.py"
    cfg.write_text(textwrap.dedent("""
        from dataclasses import dataclass

        @dataclass
        class FooConfig:
            live_field: int = 1  # documented inline
            dead_field: int = 2  # referenced nowhere
            # block comment above counts as documentation
            dead_but_commented: int = 3
            naked_dead: int = 4
    """))
    findings = knobs.audit(
        code_paths=[str(code)], liveness_paths=[str(code)],
        readme_path=str(readme), config_path=str(cfg),
    )
    by_rule = {}
    for f in findings:
        by_rule.setdefault(f.rule, []).append(f.message)
    assert len(by_rule.get("AF2K003", [])) == 3  # all but live_field
    k004 = by_rule.get("AF2K004", [])
    assert len(k004) == 1 and "naked_dead" in k004[0]


# -------------------------------------- runtime witness vs static graph


@pytest.mark.slow
def test_runtime_order_matches_static(lock_witness):
    """Drive the real threaded dispatcher with instrumented locks and
    assert every observed acquisition edge exists in the static graph —
    the auditor's model validated against runtime reality."""
    from alphafold2_tpu.config import (
        Config, DataConfig, ModelConfig, ServeConfig,
    )
    from alphafold2_tpu.serve import pipeline as pl
    from alphafold2_tpu.serve.engine import ServeEngine
    from alphafold2_tpu.serve.scheduler import AsyncServeFrontend

    cfg = Config(
        model=ModelConfig(
            dim=32, depth=1, heads=2, dim_head=16, bfloat16=False
        ),
        data=DataConfig(msa_depth=2),
        serve=ServeConfig(buckets=(8, 16), max_batch=2, mds_iters=10),
    )
    engine = ServeEngine(cfg)
    undo = lock_witness.wrap_class(
        pl.PipelineBatch, "_lock", "PipelineBatch._lock"
    )
    try:
        with AsyncServeFrontend(engine) as fe:
            lock_witness.wrap(
                fe, "_lock", "AsyncServeFrontend._lock"
            )
            handles = [
                fe.submit("ACDEFG" + "K" * (i % 3)) for i in range(8)
            ]
            for h in handles:
                assert h.result(timeout=180) is not None
            admitted = fe.stats().get("sched.inflight_admitted", 0)
    finally:
        undo()

    static_edges = {
        (src, dst) for (src, dst) in build_model().edges
    }
    for edge in lock_witness.edges:
        assert edge in static_edges, (
            f"runtime acquired {edge[1]} while holding {edge[0]}, but the "
            "static lock graph has no such edge — the auditor's model "
            "diverged from reality"
        )
    # non-vacuity: when the continuous-batching join actually fired, the
    # scheduler->membership edge must have been witnessed at runtime
    if admitted > 0:
        assert (
            "AsyncServeFrontend._lock", "PipelineBatch._lock"
        ) in lock_witness.edges
