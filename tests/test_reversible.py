"""Reversible trunk engine tests.

The reference validates its hand-written reversible backward against plain
autograd with a gradient-equality oracle (reference tests/test_reversible.py:
identical inputs through reverse=True/False, allclose on input grads).
Same protocol here: ``use_custom_vjp=False`` runs the identical coupling
under plain autodiff and must produce the same values and gradients as the
inversion-based custom backward. Plus what the reference never tests:
inversion exactness, dropout-replay exactness, and model-level integration.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from alphafold2_tpu.models.reversible import ReversibleTrunk, RevLayerPair

B, N, M, NM, D = 2, 6, 3, 5, 16


def _inputs(key):
    kx, km = jax.random.split(key)
    x = jax.random.normal(kx, (B, N, N, D))
    m = jax.random.normal(km, (B, M, NM, D))
    pair_mask = jnp.ones((B, N, N), bool).at[:, -1].set(False)
    msa_mask = jnp.ones((B, M, NM), bool).at[:, :, -1].set(False)
    return x, m, pair_mask, msa_mask


def _trunk(**kw):
    base = dict(dim=D, depth=3, heads=2, dim_head=8, use_flash=False)
    base.update(kw)
    return ReversibleTrunk(**base)


def test_forward_matches_plain_autodiff_path():
    x, m, pm, mm = _inputs(jax.random.key(0))
    rev = _trunk(use_custom_vjp=True)
    ref = _trunk(use_custom_vjp=False)
    params = rev.init(jax.random.key(1), x, m, pm, mm)
    out_rev = rev.apply(params, x, m, pm, mm)
    out_ref = ref.apply(params, x, m, pm, mm)
    for a, b in zip(jax.tree.leaves(out_rev), jax.tree.leaves(out_ref)):
        np.testing.assert_allclose(a, b, atol=1e-5)


@pytest.mark.slow
def test_reversible_grad_parity():
    """The custom (inversion-based) backward == plain autodiff, for both
    parameter and input gradients — the reference's own oracle standard
    (tests/test_reversible.py:48-52, atol 1e-3; tighter here)."""
    x, m, pm, mm = _inputs(jax.random.key(2))
    rev = _trunk(use_custom_vjp=True)
    ref = _trunk(use_custom_vjp=False)
    params = rev.init(jax.random.key(3), x, m, pm, mm)

    def loss(mod):
        def f(p, x, m):
            xo, mo = mod.apply(p, x, m, pm, mm)
            return jnp.sum(xo**2) + jnp.sum(mo**2)

        return f

    (gp_rev, gx_rev, gm_rev) = jax.grad(loss(rev), argnums=(0, 1, 2))(params, x, m)
    (gp_ref, gx_ref, gm_ref) = jax.grad(loss(ref), argnums=(0, 1, 2))(params, x, m)

    np.testing.assert_allclose(gx_rev, gx_ref, atol=2e-4, rtol=1e-3)
    np.testing.assert_allclose(gm_rev, gm_ref, atol=2e-4, rtol=1e-3)
    flat_rev = jax.tree.leaves(gp_rev)
    flat_ref = jax.tree.leaves(gp_ref)
    assert len(flat_rev) == len(flat_ref)
    for a, b in zip(flat_rev, flat_ref):
        np.testing.assert_allclose(a, b, atol=2e-4, rtol=1e-3)


def test_layer_inversion_exact():
    """invert(forward(h)) == h to float32 roundoff."""
    x, m, pm, mm = _inputs(jax.random.key(4))
    layer = RevLayerPair(dim=D, heads=2, dim_head=8, use_flash=False)
    h = (x, x * 0.5, m, m * 0.5)
    params = layer.init(jax.random.key(5), h, pm, mm, True)
    h_out = layer.apply(params, h, pm, mm, True)
    h_back = layer.apply(params, h_out, pm, mm, True, method=RevLayerPair.invert)
    for a, b in zip(h, h_back):
        np.testing.assert_allclose(a, b, atol=1e-4)


@pytest.mark.slow
def test_grad_parity_with_dropout():
    """Dropout replay by PRNG key: the custom backward re-runs blocks with
    the same per-layer keys, so gradients still match plain autodiff (the
    capability the reference needs CUDA RNG capture for, reversible.py:26-56)."""
    x, m, pm, mm = _inputs(jax.random.key(6))
    rev = _trunk(use_custom_vjp=True, attn_dropout=0.1, ff_dropout=0.1)
    ref = _trunk(use_custom_vjp=False, attn_dropout=0.1, ff_dropout=0.1)
    params = rev.init(jax.random.key(7), x, m, pm, mm)
    dk = jax.random.key(8)

    def loss(mod):
        def f(p):
            xo, mo = mod.apply(
                p, x, m, pm, mm, False, rngs={"dropout": dk}
            )
            return jnp.sum(xo**2) + jnp.sum(mo**2)

        return f

    gp_rev = jax.grad(loss(rev))(params)
    gp_ref = jax.grad(loss(ref))(params)
    for a, b in zip(jax.tree.leaves(gp_rev), jax.tree.leaves(gp_ref)):
        np.testing.assert_allclose(a, b, atol=2e-4, rtol=1e-3)


@pytest.mark.slow
def test_bf16_compute_keeps_f32_carry_and_grad_parity():
    """Under bf16 compute the carried state stays float32 (inversion error
    must not compound in the low-precision carry), and the custom backward
    still matches plain autodiff."""
    x, m, pm, mm = _inputs(jax.random.key(12))
    rev = _trunk(use_custom_vjp=True, dtype=jnp.bfloat16, depth=2)
    ref = _trunk(use_custom_vjp=False, dtype=jnp.bfloat16, depth=2)
    params = rev.init(jax.random.key(13), x, m, pm, mm)
    xo, mo = rev.apply(params, x, m, pm, mm)
    assert xo.dtype == jnp.float32 and mo.dtype == jnp.float32

    def loss(mod):
        def f(p):
            xo, mo = mod.apply(p, x, m, pm, mm)
            return jnp.sum(xo.astype(jnp.float32) ** 2) + jnp.sum(
                mo.astype(jnp.float32) ** 2
            )

        return f

    gp_rev = jax.grad(loss(rev))(params)
    gp_ref = jax.grad(loss(ref))(params)
    for a, b in zip(jax.tree.leaves(gp_rev), jax.tree.leaves(gp_ref)):
        a = np.asarray(a, np.float32)
        b = np.asarray(b, np.float32)
        # bf16 compute carries ~3 significant digits, and the reversible
        # path recomputes activations by inversion, so the two backward
        # graphs round differently in the low bits: bound the error
        # against the leaf's own gradient SCALE — per-leaf relative L2
        # plus a coarse elementwise cap. (An elementwise rtol demands
        # bf16-impossible precision wherever a near-zero grad sits next
        # to O(10) ones; a wrong backward FORMULA errs at O(scale) and
        # still trips both bounds.)
        scale = max(np.abs(b).max(), 1.0)
        rel_l2 = np.linalg.norm(a - b) / max(np.linalg.norm(b), 1e-6)
        assert rel_l2 < 2e-2, rel_l2
        np.testing.assert_allclose(a, b, atol=0.1 * scale, rtol=0)


def test_no_masks_path():
    x, m, _, _ = _inputs(jax.random.key(9))
    rev = _trunk(depth=2)
    params = rev.init(jax.random.key(10), x, m)
    xo, mo = jax.jit(lambda p: rev.apply(p, x, m))(params)
    assert xo.shape == x.shape and mo.shape == m.shape
    assert np.isfinite(np.asarray(xo)).all()


@pytest.mark.slow
def test_model_reversible_trains():
    """Alphafold2(reversible=True): forward + one grad step, finite, and the
    distogram head shape is unchanged."""
    from alphafold2_tpu.models import Alphafold2

    model = Alphafold2(
        dim=32, depth=2, heads=2, dim_head=16, max_seq_len=32,
        reversible=True, msa_tie_row_attn=True, use_flash=False,
    )
    k = jax.random.key(11)
    seq = jax.random.randint(jax.random.fold_in(k, 1), (1, 8), 0, 21)
    msa = jax.random.randint(jax.random.fold_in(k, 2), (1, 3, 8), 0, 21)
    mask = jnp.ones((1, 8), bool)
    msa_mask = jnp.ones((1, 3, 8), bool)
    params = model.init(k, seq, msa, mask=mask, msa_mask=msa_mask)

    def loss(p):
        out = model.apply(p, seq, msa, mask=mask, msa_mask=msa_mask)
        return jnp.mean(out**2)

    l, g = jax.jit(jax.value_and_grad(loss))(params)
    assert np.isfinite(float(l))
    gn = float(jnp.sqrt(sum(jnp.sum(x**2) for x in jax.tree.leaves(g))))
    assert np.isfinite(gn) and gn > 0


def test_reversible_requires_msa():
    from alphafold2_tpu.models.trunk import Trunk

    x = jnp.zeros((1, 4, 4, D))
    t = Trunk(dim=D, depth=1, heads=2, dim_head=8, reversible=True)
    with pytest.raises(ValueError):
        t.init(jax.random.key(0), x, None)


def test_reversible_rejects_grid_parallel():
    # the reversible engine's axial passes run dense: combining it with the
    # 2D pair-grid sharding would silently all-gather the pair state and
    # lose the memory benefit — must refuse, like context_parallel does
    from alphafold2_tpu.models.trunk import Trunk

    x = jnp.zeros((1, 4, 4, D))
    m = jnp.zeros((1, 2, 4, D))
    t = Trunk(dim=D, depth=1, heads=2, dim_head=8, reversible=True,
              grid_parallel=True)
    with pytest.raises(ValueError, match="grid_parallel"):
        t.init(jax.random.key(0), x, m)


@pytest.mark.slow
def test_reversible_with_sparse_attention():
    """Composition: block-sparse pair attention (its own custom-vjp Pallas
    path) inside the reversible engine's hand-scheduled backward. Values and
    grads must match the plain-autodiff reversible path."""
    from alphafold2_tpu.ops.sparse import BlockSparseConfig

    _, m, _, mm = _inputs(jax.random.key(20))
    # sparse layouts need block-size-aligned grids: 8x8 with block 4
    x = jax.random.normal(jax.random.key(21), (B, 8, 8, D))
    pm = jnp.ones((B, 8, 8), bool)
    kw = dict(
        dim=D, depth=2, heads=2, dim_head=8, use_flash=False,
        sparse_attn=True, seq_len=8,
        sparse_config=BlockSparseConfig(block_size=4, num_random_blocks=0),
    )
    rev = ReversibleTrunk(use_custom_vjp=True, **kw)
    ref = ReversibleTrunk(use_custom_vjp=False, **kw)
    params = rev.init(jax.random.key(22), x, m, pm, mm)

    def loss(mod):
        def f(p):
            xo, mo = mod.apply(p, x, m, pm, mm)
            return jnp.sum(xo**2) + jnp.sum(mo**2)

        return f

    l_rev = float(loss(rev)(params))
    l_ref = float(loss(ref)(params))
    assert np.isclose(l_rev, l_ref, rtol=1e-5)
    gp_rev = jax.grad(loss(rev))(params)
    gp_ref = jax.grad(loss(ref))(params)
    for a, b in zip(jax.tree.leaves(gp_rev), jax.tree.leaves(gp_ref)):
        np.testing.assert_allclose(a, b, atol=3e-4, rtol=1e-3)
