"""Mesh-sharded serving tests: the mesh-gated long-chain ladder, the
(bucket, batch, mesh) executable cache key, explicit-sharding dispatch, and
cross-mesh parity.

Parity contract (and why it is stated the way it is): the sharded trunk is
the SAME model function — its outputs (distogram logits, confidence
weights) match the single-device executable to ~1e-7, far inside the 1e-4
acceptance bound, for every shared bucket including padded batch slots.
The realized COORDINATES are a different matter: MDS + dihedral-based atom
placement on an untrained model's random distogram is chaotic — it
amplifies even the float-reassociation noise between two XLA programs of
the same computation (measured here: a 1e-6 perturbation of one parameter
moves single-device coordinates as far as the whole sharded-vs-single gap).
So coordinates are asserted finite/valid, model outputs are asserted at
1e-4, and the chaos is pinned by an attribution test rather than papered
over with a giant tolerance."""

import numpy as np
import pytest

import jax

from alphafold2_tpu.config import (
    Config,
    DataConfig,
    ModelConfig,
    ServeConfig,
)
from alphafold2_tpu.parallel.grid_parallel import make_grid_mesh
from alphafold2_tpu.serve import ServeEngine, ServeRequest, result_key


def _cfg(buckets=(8, 16), max_batch=2, grid=False, **serve_kw):
    serve_kw.setdefault("mds_iters", 20)
    serve_kw.setdefault("return_distogram", True)
    return Config(
        model=ModelConfig(dim=32, depth=1, heads=2, dim_head=16,
                          max_seq_len=3 * 64, bfloat16=False,
                          grid_parallel=grid),
        data=DataConfig(msa_depth=2),
        serve=ServeConfig(buckets=buckets, max_batch=max_batch, **serve_kw),
    )


@pytest.fixture(scope="module")
def single():
    return ServeEngine(_cfg())


@pytest.fixture(scope="module")
def mesh():
    return make_grid_mesh(1, 2, 2, devices=jax.devices()[:4])


@pytest.fixture(scope="module")
def sharded(single, mesh):
    return ServeEngine(
        _cfg(grid=True, long_buckets=(24,), long_max_batch=1),
        params=single.params, mesh=mesh,
    )


# ------------------------------------------------------------- ladder gate


def test_long_buckets_rejected_without_mesh():
    with pytest.raises(ValueError, match="require a device mesh"):
        ServeEngine(_cfg(long_buckets=(24,)))


def test_long_buckets_admitted_with_mesh(sharded):
    assert sharded.buckets == (8, 16, 24)
    assert sharded.long_buckets == (24,)
    assert sharded.batch_for(8) == 2 and sharded.batch_for(24) == 1
    assert sharded.mesh_desc == "dp1.spr2.spc2"


def test_long_request_rejected_single_device(single):
    with pytest.raises(ValueError, match="exceeds the largest bucket"):
        single.predict_many(["A" * 20])


def test_grid_mesh_requires_grid_parallel_model(mesh):
    with pytest.raises(ValueError, match="grid_parallel"):
        ServeEngine(_cfg(grid=False), mesh=mesh)


def test_mesh_batch_divisibility_validated():
    mesh = make_grid_mesh(2, 1, 2, devices=jax.devices()[:4])
    with pytest.raises(ValueError, match="divide by the mesh's dp axis"):
        ServeEngine(_cfg(grid=True, max_batch=3), mesh=mesh)


# -------------------------------------------------------- cache / identity


def test_executable_cache_keyed_by_mesh(sharded):
    sharded.predict_many([ServeRequest("ACDEFG", seed=0)])
    keys = list(sharded._executables)
    assert all(k[2] == "dp1.spr2.spc2" for k in keys), keys
    # compile records carry the mesh identity + per-device memory analysis
    rec = sharded.compile_records[0]
    assert rec["mesh"] == "dp1.spr2.spc2"
    assert rec.get("program_bytes", 0) > 0


def test_result_cache_key_carries_mesh():
    assert result_key("ACD", 1, None) != result_key("ACD", 1, "dp1.spr2.spc2")


# ------------------------------------------------------- cross-mesh parity


def test_cross_mesh_model_output_parity(single, sharded):
    """Sharded predict_many output matches single-device output within
    1e-4 for every shared bucket: the model outputs (distogram logits and
    confidence weights) are the parity surface — measured margin is ~1e-7.
    """
    for seed, seq in enumerate(["ACDEFG", "MKVLITDSW", "ACDEFGHKLMNPQR"]):
        a = single.predict_many([ServeRequest(seq, seed=seed)])[0]
        b = sharded.predict_many([ServeRequest(seq, seed=seed)])[0]
        assert a.bucket == b.bucket  # shared rung
        np.testing.assert_allclose(b.weights, a.weights, atol=1e-4)
        np.testing.assert_allclose(b.distogram, a.distogram, atol=1e-4)
        # realized coordinates: finite and correctly shaped on both (their
        # pointwise comparison is chaos-bound — see module docstring and
        # test_realization_chaos_attribution)
        assert np.all(np.isfinite(b.atom14))
        assert b.atom14.shape == a.atom14.shape


def test_cross_mesh_parity_includes_padded_batch_slots(single, sharded):
    """The same request co-batched beside a partner (and beside the
    fully-masked dummy slot padding creates) must produce the same model
    outputs as solo, on the mesh, and match single-device at 1e-4."""
    req = ServeRequest("ACDEFG", seed=11)
    solo = sharded.predict_many([req])[0]
    batched = sharded.predict_many(
        [ServeRequest("MKVLIT", seed=5), req]
    )[1]
    # same sharded executable shape -> padding exactness is bitwise-level
    np.testing.assert_allclose(batched.weights, solo.weights, atol=1e-6)
    np.testing.assert_allclose(batched.atom14, solo.atom14, atol=1e-5)
    ref = single.predict_many([req])[0]
    np.testing.assert_allclose(batched.weights, ref.weights, atol=1e-4)
    np.testing.assert_allclose(batched.distogram, ref.distogram, atol=1e-4)


def test_long_rung_serves_end_to_end(sharded):
    """A request only the mesh ladder admits (20 residues > top regular
    rung 16) dispatches on the long rung and returns a valid structure."""
    r = sharded.predict_many([ServeRequest("ACDEFGHKLMNPQRSTVWYA", seed=3)])[0]
    assert r.bucket == 24 and r.status == "ok"
    assert r.atom14.shape == (20, 14, 3)
    assert np.all(np.isfinite(r.atom14))


def test_realization_chaos_attribution(single):
    """Why coordinates are not pointwise-compared across meshes: the
    distogram->MDS->dihedral pipeline on an untrained model amplifies a
    1e-6 single-parameter perturbation into coordinate changes of the same
    order as the sharded-vs-single gap — the gap is the pipeline's own
    noise floor, not a sharding defect. (The model outputs, by contrast,
    move by ~1e-7 under sharding — see the parity tests above.)"""
    req = [ServeRequest("ACDEFG", seed=3)]
    base = single.predict_many(req)[0]
    perturbed = jax.tree.map(lambda x: x, single.params)
    leaves, treedef = jax.tree_util.tree_flatten(perturbed)
    leaves = [leaves[0] + 1e-6] + leaves[1:]
    eng2 = ServeEngine(_cfg(), params=jax.tree_util.tree_unflatten(
        treedef, leaves
    ))
    moved = eng2.predict_many(req)[0]
    # the trunk barely moves...
    assert np.abs(moved.weights - base.weights).max() < 1e-3
    # ...but the realized coordinates move orders of magnitude more than
    # the weights did: the amplification is intrinsic, not sharding-made
    w_delta = max(float(np.abs(moved.weights - base.weights).max()), 1e-9)
    c_delta = float(np.abs(moved.atom14 - base.atom14).max())
    assert c_delta > 10 * w_delta


# ------------------------------------------------------ scheduler on mesh


def test_frontend_over_sharded_engine(sharded):
    """The async frontend threads mesh identity through its dispatch and
    result-cache keys, and forms long-rung batches at long_max_batch."""
    from alphafold2_tpu.serve import AsyncServeFrontend

    fe = AsyncServeFrontend(sharded, start=False)
    h_long = fe.submit(ServeRequest("ACDEFGHKLMNPQRSTVWYA", seed=9))
    h_dup = fe.submit(ServeRequest("ACDEFGHKLMNPQRSTVWYA", seed=9))
    fe.pump()  # long rung fills at long_max_batch=1 -> dispatches alone
    r1, r2 = h_long.result(timeout=120), h_dup.result(timeout=120)
    assert r1.status == "ok" and r1.bucket == 24
    assert r2.status == "ok" and r2.cache_hit  # in-flight dedup, mesh key
    assert fe.cache.peek(
        result_key("ACDEFGHKLMNPQRSTVWYA", 9, sharded.mesh_desc)
    ) is not None
    fe.close()
