"""Perf regression gate tests: observe.regress verdicts (pass / regress /
invalid-record / missing-baseline), the scripts/bench_compare.py CLI,
the unified observe.flops accounting, and obs_report's train summary."""

import importlib
import json
import os
import sys

import pytest

from alphafold2_tpu.observe import regress

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

BASE = {
    "metric": "serve residues/sec tiny", "device": "cpu", "mode": "serve",
    "value": 100.0, "p50_ms": 10.0, "p95_ms": 20.0, "mfu": 0.2,
}


# ------------------------------------------------------------- regress core


def test_compare_pass():
    v = regress.compare({**BASE, "value": 95.0, "p95_ms": 21.0}, BASE)
    assert v["verdict"] == "pass"
    assert {"value", "p50_ms", "p95_ms", "mfu"} <= {
        c["name"] for c in v["comparisons"]
    }
    assert v["regressions"] == []


def test_compare_regress_value_and_latency():
    v = regress.compare({**BASE, "value": 50.0}, BASE)
    assert v["verdict"] == "regress" and v["regressions"] == ["value"]
    v = regress.compare({**BASE, "p95_ms": 200.0}, BASE)
    assert v["verdict"] == "regress" and v["regressions"] == ["p95_ms"]


def test_compare_invalid_records():
    err = {"metric": BASE["metric"], "value": 0.0,
           "error": "deadline 1500s exceeded during phase 'backend_init'",
           "phase": "backend_init"}
    v = regress.compare(err, BASE)
    assert v["verdict"] == "no-data"
    assert "current record invalid" in v["reason"]
    for marker in ({"implausible": True}, {"clock_suspect": True},
                   {"liveness": "dead"}):
        assert regress.compare({**BASE, **marker}, BASE)["verdict"] == "no-data"
    # the committed withdrawn train baseline's shape (value null + invalid)
    withdrawn = {"metric": "m", "value": None, "invalid": "withdrawn: ..."}
    v = regress.compare({"metric": "m", "value": 5.0}, withdrawn)
    assert v["verdict"] == "no-data"
    assert "baseline record invalid" in v["reason"]


def test_compare_is_device_and_methodology_keyed():
    v = regress.compare({**BASE, "device": "TPU v5 lite"}, BASE)
    assert v["verdict"] == "no-data" and "device" in v["reason"]
    v = regress.compare({**BASE, "metric": "other"}, BASE)
    assert v["verdict"] == "no-data" and "metric label" in v["reason"]
    v = regress.compare({**BASE, "ingraph": 4}, {**BASE, "ingraph": 8})
    assert v["verdict"] == "no-data" and "ingraph" in v["reason"]
    assert regress.compare(BASE, None)["verdict"] == "no-data"


def test_threshold_overrides():
    th = regress.parse_threshold_overrides(["value=0.6", "p95_ms=lower:2.0"])
    assert th["value"] == ("higher", 0.6)
    assert th["p95_ms"] == ("lower", 2.0)
    assert regress.compare({**BASE, "value": 50.0}, BASE, th)["verdict"] == "pass"
    with pytest.raises(ValueError):
        regress.parse_threshold_overrides(["value"])
    with pytest.raises(ValueError):
        regress.parse_threshold_overrides(["value=sideways:0.5"])


# ------------------------------------------------------------------ the CLI


@pytest.fixture()
def bench_compare(monkeypatch):
    monkeypatch.syspath_prepend(os.path.join(REPO, "scripts"))
    sys.modules.pop("bench_compare", None)
    yield importlib.import_module("bench_compare")
    sys.modules.pop("bench_compare", None)


def _write(tmp_path, name, rec):
    p = tmp_path / name
    p.write_text(json.dumps(rec))
    return str(p)


def test_cli_pass_and_regress(bench_compare, tmp_path, capsys):
    cur = _write(tmp_path, "cur.json", {**BASE, "value": 95.0})
    base = _write(tmp_path, "base.json", BASE)
    assert bench_compare.main([cur, "--baseline", base]) == 0
    assert json.loads(capsys.readouterr().out)["verdict"] == "pass"

    cur = _write(tmp_path, "cur2.json", {**BASE, "value": 10.0})
    assert bench_compare.main([cur, "--baseline", base]) == 1
    captured = capsys.readouterr()
    assert json.loads(captured.out)["verdict"] == "regress"
    assert "REGRESSION" in captured.err


def test_cli_missing_baseline_and_bad_input(bench_compare, tmp_path, capsys):
    cur = _write(tmp_path, "cur.json", BASE)
    missing = str(tmp_path / "nope.json")
    assert bench_compare.main([cur, "--baseline", missing]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["verdict"] == "no-data" and "missing baseline" in out["reason"]

    bad = tmp_path / "bad.json"
    bad.write_text("not json at all")
    assert bench_compare.main([str(bad), "--baseline", missing]) == 2


def test_cli_invalid_bench_record_verdict(bench_compare, tmp_path, capsys):
    # the exact shape the bench watchdog emits (cf. BENCH_r05.json)
    rec = {"metric": "residue-pairs/sec/chip crop=256 ...", "value": 0.0,
           "unit": "pairs/sec", "vs_baseline": 0.0,
           "vs_baseline_valid": False,
           "error": "deadline 1500s exceeded during phase "
                    "'first_light:backend_init'",
           "phase": "first_light:backend_init"}
    cur = _write(tmp_path, "cur.json", rec)
    base = _write(tmp_path, "base.json", BASE)
    assert bench_compare.main([cur, "--baseline", base]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["verdict"] == "no-data" and "invalid" in out["reason"]


def test_cli_default_baseline_routing(bench_compare):
    assert bench_compare.default_baseline_path({"mode": "serve"}).endswith(
        "bench_serve_baseline.json"
    )
    assert bench_compare.default_baseline_path(
        {"mode": "serve-async"}
    ).endswith("bench_serve_async_baseline.json")
    assert bench_compare.default_baseline_path({}).endswith(
        "bench_baseline.json"
    )


# ------------------------------------------------- dtype / kernel keying

KERNELS_BASE = {
    "metric": "kernels fused-vs-stock speedup axial=... tied=... iters=5",
    "device": "cpu", "mode": "kernels", "kernels": "auto",
    "value": 0.5, "fused_ms_total": 15.0, "stock_ms_total": 8.0,
    "interpret": True,
}


def test_kernels_threshold_selection_and_cliff():
    """--mode kernels records select KERNELS_THRESHOLDS: the geomean
    speedup is gated at 0.5x (an interpret-path blowup or a silent
    fall-back-to-dense halves it), timings at wide cross-machine
    tolerance."""
    assert regress.thresholds_for(KERNELS_BASE) is regress.KERNELS_THRESHOLDS
    ok = regress.compare({**KERNELS_BASE, "value": 0.3}, KERNELS_BASE)
    assert ok["verdict"] == "pass"  # 0.6x of baseline: inside tolerance
    cliff = regress.compare({**KERNELS_BASE, "value": 0.2}, KERNELS_BASE)
    assert cliff["verdict"] == "regress" and "value" in cliff["regressions"]


def test_dtype_and_kernel_records_never_cross_compare():
    """A bf16 record vs an f32 one — or two different kernel policies — is
    no-data, exactly like a mesh mismatch: precision/kernel changes are
    explicit diffs, never silent ratio drift."""
    bf16 = {**BASE, "dtype": "bfloat16"}
    v = regress.compare(bf16, BASE)
    assert v["verdict"] == "no-data" and "dtype mismatch" in v["reason"]
    v = regress.compare(BASE, bf16)
    assert v["verdict"] == "no-data" and "dtype mismatch" in v["reason"]
    pol = {**BASE, "kernels": "tied_row=pallas"}
    v = regress.compare(pol, BASE)
    assert v["verdict"] == "no-data" and "kernels mismatch" in v["reason"]
    # matching variant keys compare normally
    v = regress.compare({**bf16, "value": 95.0}, bf16)
    assert v["verdict"] == "pass"


def test_cli_kernels_and_bf16_baseline_routing(bench_compare):
    assert bench_compare.default_baseline_path(
        {"mode": "kernels"}
    ).endswith("bench_kernels_baseline.json")
    assert bench_compare.default_baseline_path(
        {"mode": "serve", "dtype": "bfloat16"}
    ).endswith("bench_serve_bf16_baseline.json")
    # mesh wins over dtype (the sharded flagship owns its baseline file)
    assert bench_compare.default_baseline_path(
        {"mode": "serve", "dtype": "bfloat16", "mesh": "dp1.spr2.spc4"}
    ).endswith("bench_serve_mesh_baseline.json")


def test_committed_kernels_and_bf16_baselines_are_valid():
    """The committed kernel-microbench and bf16 serve baselines must be
    usable measurements carrying their variant keys."""
    with open(os.path.join(REPO, "bench_kernels_baseline.json")) as f:
        kb = json.load(f)
    assert regress.record_invalid_reason(kb) is None
    assert kb["mode"] == "kernels" and "kernels" in kb
    assert len(kb["shapes"]) == 6
    with open(os.path.join(REPO, "bench_serve_bf16_baseline.json")) as f:
        sb = json.load(f)
    assert regress.record_invalid_reason(sb) is None
    assert sb["dtype"] == "bfloat16" and "dtype=bfloat16" in sb["metric"]
    assert sb["kernels"] == "tied_row=pallas"
    assert sb["flops_by_kernel"]["tied_row"] > 0


# ------------------------------------------------------------ mesh keying

MESH_BASE = {
    "metric": "serve residues/sec tiny mesh=1x2x4 long=512x1",
    "device": "cpu", "mode": "serve", "mesh": "dp1.spr2.spc4",
    "value": 4.0, "p50_ms": 1500.0, "p95_ms": 170000.0, "p99_ms": 170000.0,
    "per_device_program_bytes": 380_000_000,
}


def test_mesh_records_never_compare_across_meshes():
    """A sharded record vs a single-device one (or two mesh shapes) is
    no-data, whatever the device kind says."""
    v = regress.compare({**MESH_BASE, "mesh": None}, MESH_BASE)
    assert v["verdict"] == "no-data" and "mesh mismatch" in v["reason"]
    v = regress.compare({**MESH_BASE, "mesh": "dp1.spr2.spc2"}, MESH_BASE)
    assert v["verdict"] == "no-data" and "mesh mismatch" in v["reason"]


def test_mesh_threshold_selection_and_memory_cliff():
    """Mesh-serve records select SERVE_MESH_THRESHOLDS: wide cross-machine
    perf tolerances, but per-device program bytes (deterministic per
    program) gated at 2x — the forgot-the-sharding cliff."""
    assert regress.thresholds_for(MESH_BASE) is regress.SERVE_MESH_THRESHOLDS
    assert regress.thresholds_for(BASE) is regress.DEFAULT_THRESHOLDS
    ok = regress.compare({**MESH_BASE, "value": 2.0}, MESH_BASE)
    assert ok["verdict"] == "pass"  # 2x slower machine: inside tolerance
    cliff = regress.compare(
        {**MESH_BASE, "per_device_program_bytes": 8 * 380_000_000},
        MESH_BASE,
    )
    assert cliff["verdict"] == "regress"
    assert cliff["regressions"] == ["per_device_program_bytes"]


def test_cli_mesh_baseline_routing(bench_compare):
    assert bench_compare.default_baseline_path(
        {"mode": "serve", "mesh": "dp1.spr2.spc4"}
    ).endswith("bench_serve_mesh_baseline.json")
    assert bench_compare.default_baseline_path({"mode": "serve"}).endswith(
        "bench_serve_baseline.json"
    )


def test_committed_mesh_baseline_is_valid_and_self_consistent():
    """The committed mesh-keyed baseline must be a usable measurement
    (regress validity taxonomy) carrying the acceptance fields: mesh
    shape, per-device memory, and MFU accounting."""
    with open(os.path.join(REPO, "bench_serve_mesh_baseline.json")) as f:
        base = json.load(f)
    assert regress.record_invalid_reason(base) is None
    assert base["mesh"] == "dp1.spr2.spc4" and base["mesh_devices"] == 8
    assert base["per_device_program_bytes"] > 0
    assert base["mfu"] is not None and base["mfu_basis"]
    assert any(
        c["bucket"] >= 512 and c.get("mesh") for c in base["compile_records"]
    )
    v = regress.compare(base, base, regress.thresholds_for(base))
    assert v["verdict"] == "pass"


# -------------------------------------------------- serve-async thresholds

ASYNC_BASE = {
    "metric": "serve-async residues/sec tiny", "device": "cpu",
    "mode": "serve-async", "value": 100.0, "goodput_rps": 8.0,
    "p50_ms": 50.0, "p95_ms": 100.0, "p99_ms": 150.0,
    "rejection_rate": 0.05,
}


def test_serve_async_threshold_selection():
    """The gate picks the serve-async direction table by record shape, so
    open-loop records get real per-metric verdicts, not no-data."""
    assert regress.thresholds_for(ASYNC_BASE) is regress.SERVE_ASYNC_THRESHOLDS
    assert regress.thresholds_for(BASE) is regress.DEFAULT_THRESHOLDS
    assert regress.thresholds_for(None) is regress.DEFAULT_THRESHOLDS
    assert {"goodput_rps", "rejection_rate", "value", "p99_ms"} <= set(
        regress.SERVE_ASYNC_THRESHOLDS
    )


def test_compare_serve_async_directions():
    thr = regress.SERVE_ASYNC_THRESHOLDS
    v = regress.compare(ASYNC_BASE, ASYNC_BASE, thr)
    assert v["verdict"] == "pass"
    assert {"goodput_rps", "rejection_rate"} <= {
        c["name"] for c in v["comparisons"]
    }
    # goodput collapse regresses (higher-is-better)
    v = regress.compare({**ASYNC_BASE, "goodput_rps": 1.0}, ASYNC_BASE, thr)
    assert v["verdict"] == "regress" and "goodput_rps" in v["regressions"]
    # rejection storm regresses (lower-is-better)
    v = regress.compare({**ASYNC_BASE, "rejection_rate": 0.5}, ASYNC_BASE, thr)
    assert v["verdict"] == "regress" and "rejection_rate" in v["regressions"]
    # a zero-rejection baseline cannot gate the ratio (explicitly ok)
    v = regress.compare(
        {**ASYNC_BASE, "rejection_rate": 0.5},
        {**ASYNC_BASE, "rejection_rate": 0.0}, thr,
    )
    assert v["verdict"] == "pass"


def test_cli_uses_serve_async_thresholds(bench_compare, tmp_path, capsys):
    """p95 2.5x worse: within the generous default-table tolerance? No —
    and for serve-async shapes the CLI must gate goodput too."""
    cur = _write(tmp_path, "cur.json", {**ASYNC_BASE, "goodput_rps": 2.0})
    base = _write(tmp_path, "base.json", ASYNC_BASE)
    assert bench_compare.main([cur, "--baseline", base]) == 1
    out = json.loads(capsys.readouterr().out)
    assert out["verdict"] == "regress" and "goodput_rps" in out["regressions"]


def test_cli_threshold_override(bench_compare, tmp_path, capsys):
    cur = _write(tmp_path, "cur.json", {**BASE, "value": 50.0})
    base = _write(tmp_path, "base.json", BASE)
    assert bench_compare.main(
        [cur, "--baseline", base, "--threshold", "value=0.6"]
    ) == 0
    assert json.loads(capsys.readouterr().out)["verdict"] == "pass"


# --------------------------------------------------------- unified flops


def test_flops_single_parser_and_mfu():
    import jax
    import jax.numpy as jnp

    from alphafold2_tpu.observe import flops

    compiled = jax.jit(lambda x: x @ x).lower(jnp.ones((32, 32))).compile()
    costs = flops.executable_costs(compiled)
    assert flops.step_flops(compiled) == costs["flops"]
    if costs["flops"] is not None:  # CPU cost analysis exposes flops
        assert costs["flops"] > 0 and costs["bytes_accessed"] > 0
    # MFU: explicit peak works; unknown device (CPU) yields None
    assert flops.mfu(1e12, 1.0, peak=2e12) == 0.5
    assert flops.mfu(None, 1.0, peak=2e12) is None
    assert flops.mfu(1e12, 0.0, peak=2e12) is None
    assert flops.device_peak_flops() is None  # CPU is not in the peak table
    assert flops.estimate_mfu(compiled, 1.0) is None

    # bench.py sources flops/MFU from observe.flops (single parser in tree)
    import bench

    assert bench._step_flops is flops.step_flops
    assert bench._estimate_mfu is flops.estimate_mfu
    assert bench._PEAK_FLOPS is flops.PEAK_FLOPS


def test_cost_analysis_list_form_and_failure():
    from alphafold2_tpu.observe import flops

    class ListCompiled:  # older jax: one dict per device
        def cost_analysis(self):
            return [{"flops": 7.0, "bytes accessed": 3.0}]

    class Broken:
        def cost_analysis(self):
            raise RuntimeError("no cost analysis on this backend")

    assert flops.step_flops(ListCompiled()) == 7.0
    assert flops.executable_costs(ListCompiled())["bytes_accessed"] == 3.0
    assert flops.step_flops(Broken()) is None
    assert flops.executable_costs(Broken()) == {
        "flops": None, "bytes_accessed": None
    }


# ------------------------------------------------ obs_report train summary


@pytest.fixture()
def obs_report(monkeypatch):
    monkeypatch.syspath_prepend(os.path.join(REPO, "scripts"))
    sys.modules.pop("obs_report", None)
    yield importlib.import_module("obs_report")
    sys.modules.pop("obs_report", None)


def test_obs_report_train_summary(obs_report, tmp_path, capsys):
    nan = float("nan")
    recs = [
        {"step": 0, "time": 1.0, "compile_s": 2.5, "step_flops": 1e9},
        {"step": 0, "time": 1.0, "loss": 4.0, "grad_norm": 2.0,
         "grads_ok": 1.0, "skipped": 0.0, "grad_norm/trunk": 1.5,
         "first_step_s": 0.5},
        {"step": 1, "time": 2.0, "loss": nan, "grad_norm": nan,
         "grads_ok": 0.0, "skipped": 1.0, "grad_norm/trunk": nan,
         "steps_per_sec": 10.0},
        {"step": 1, "time": 2.0, "event": "nan_triage",
         "first_nonfinite": "trunk.layer_0.pair",
         "nonfinite": ["trunk.layer_0.pair"],
         "numerics/trunk.layer_0.pair/nan_count": 8.0,
         "numerics/trunk.layer_0.pair/l2": 0.0},
        {"step": 2, "time": 3.0, "loss": 3.5, "grad_norm": 1.8,
         "grads_ok": 1.0, "skipped": 1.0, "steps_per_sec": 12.0},
    ]
    path = tmp_path / "metrics.jsonl"
    path.write_text("\n".join(json.dumps(r) for r in recs) + "\n")
    assert obs_report.main([str(path)]) == 0
    out = capsys.readouterr().out
    assert "-- train (" in out
    assert "loss:      4 -> 3.5" in out
    assert "skipped steps: 1 total (1 of the logged steps" in out
    assert "first step: 500.00ms" in out
    assert "step compile: 2.500s" in out
    assert "per-group norms: trunk" in out
    assert "numerics anomalies" in out and "trunk.layer_0.pair" in out
    assert "nan_triage @ step 1: first non-finite = trunk.layer_0.pair" in out
    # the per-tensor numerics keys are summarized, not dumped one by one
    assert "numerics/trunk.layer_0.pair/nan_count =" not in out
