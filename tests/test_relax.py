"""Native relaxation tests: energy decreases, ideal bond geometry is
approached, masking freezes padded atoms, and the refinement CLI's native
path round-trips a PDB. (The reference's FastRelax was a NotImplementedError
stub — this capability is beyond-reference; the stub contract itself is
covered by driving scripts/refinement.py without pyrosetta.)"""

import os
import subprocess
import sys
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from alphafold2_tpu.utils.relax import backbone_energy, fast_relax

REPO = Path(__file__).resolve().parents[1]


def _noisy_backbone(key, L=8, noise=0.3):
    """A roughly-extended chain with ~ideal spacing, perturbed."""
    ideal = jnp.array([1.458, 1.525, 1.329])
    steps = jnp.tile(ideal, L)[: L * 3 - 1]
    x = jnp.concatenate([jnp.zeros((1,)), jnp.cumsum(steps)])
    base = jnp.stack([x, jnp.zeros_like(x), jnp.zeros_like(x)], -1)
    return base[None] + noise * jax.random.normal(key, (1, L * 3, 3))


def test_relax_decreases_energy_and_fixes_bonds():
    bb = _noisy_backbone(jax.random.key(0))
    res = jax.jit(lambda c: fast_relax(c, iters=150))(bb)
    e0 = float(res.energy_history[0, 0])
    e1 = float(res.energy[0])
    assert e1 < e0 * 0.5, (e0, e1)

    def bond_rmse(c):
        d = jnp.linalg.norm(c[0, 1:] - c[0, :-1], axis=-1)
        ideal = jnp.tile(jnp.array([1.458, 1.525, 1.329]), d.shape[0] // 3 + 1)[
            : d.shape[0]
        ]
        return float(jnp.sqrt(jnp.mean((d - ideal) ** 2)))

    assert bond_rmse(res.coords) < bond_rmse(bb) * 0.6


def test_relax_respects_mask():
    bb = _noisy_backbone(jax.random.key(1), L=6)
    mask = jnp.ones((1, 18), bool).at[:, 9:].set(False)
    res = fast_relax(bb, mask=mask, iters=20)
    np.testing.assert_allclose(
        np.asarray(res.coords[0, 9:]), np.asarray(bb[0, 9:]), atol=1e-6
    )
    assert not np.allclose(np.asarray(res.coords[0, :9]), np.asarray(bb[0, :9]))


def test_relax_is_differentiable():
    bb = _noisy_backbone(jax.random.key(2), L=4)

    def loss(c):
        return jnp.sum(fast_relax(c, iters=5).coords ** 2)

    g = jax.grad(loss)(bb)
    assert np.isfinite(np.asarray(g)).all() and float(jnp.abs(g).sum()) > 0


def test_energy_clash_term_penalizes_overlap():
    # two far-apart fragments vs collapsed-to-a-point coordinates
    spread = _noisy_backbone(jax.random.key(3), L=4, noise=0.0)
    collapsed = jnp.zeros_like(spread)
    e_spread = float(backbone_energy(spread, spread)[0])
    e_collapsed = float(backbone_energy(collapsed, collapsed)[0])
    assert e_collapsed > e_spread


def test_refinement_cli_native_roundtrip(tmp_path):
    from alphafold2_tpu.utils.pdb import backbone_to_pdb, to_pdb_string

    bb = np.asarray(_noisy_backbone(jax.random.key(4), L=5)[0]).reshape(5, 3, 3)
    pdb_in = tmp_path / "in.pdb"
    pdb_out = tmp_path / "out.pdb"
    pdb_in.write_text(to_pdb_string(backbone_to_pdb("AGAGA", bb)))
    env = dict(os.environ, AF2TPU_PLATFORM="cpu", PALLAS_AXON_POOL_IPS="")
    proc = subprocess.run(
        [sys.executable, "scripts/refinement.py", str(pdb_in), str(pdb_out),
         "--native", "--iters", "30"],
        capture_output=True, text=True, timeout=300, env=env, cwd=str(REPO),
    )
    assert proc.returncode == 0, proc.stderr[-800:]
    assert "energy" in proc.stdout
    from alphafold2_tpu.utils.pdb import load_pdb

    seq, out_bb = load_pdb(str(pdb_out)).backbone_trace()
    assert seq == "AGAGA" and out_bb.shape == (5, 3, 3)


def test_bond_term_skips_chain_breaks():
    """A gap in the reference geometry (chain break) must not be pulled to
    bond length: the bond restraint is derived from the input's own
    geometry, not blind i/i+1 adjacency."""
    a = _noisy_backbone(jax.random.key(5), L=3, noise=0.0)
    b = _noisy_backbone(jax.random.key(6), L=3, noise=0.0) + jnp.array(
        [40.0, 0.0, 0.0]
    )
    two_chains = jnp.concatenate([a, b], axis=1)  # C...N gap of ~27 A
    res = fast_relax(two_chains, iters=100)
    gap = float(jnp.linalg.norm(res.coords[0, 9] - res.coords[0, 8]))
    assert gap > 20.0, f"chain break collapsed to {gap:.2f} A"


def test_refinement_cli_stub_contract(tmp_path):
    """Without pyrosetta and without --native, the reference's contract
    holds: config loads, then NotImplementedError."""
    sys.path.insert(0, str(REPO / "scripts"))
    import importlib

    import refinement

    importlib.reload(refinement)
    if refinement.HAS_PYROSETTA:
        pytest.skip("pyrosetta installed")
    with pytest.raises(NotImplementedError):
        refinement.run_fast_relax("x.pdb", "y.pdb")


def test_chunked_clash_matches_dense():
    """The streamed (lax.map) clash path used above the dense-size threshold
    agrees with the dense formula: 30 well-separated copies of a chain have
    30x its clash-free energy (pure bond terms), computed via the chunked
    path since 1800 atoms > threshold."""
    bb = _noisy_backbone(jax.random.key(7), L=20)  # 60 atoms: dense path
    e_small = float(backbone_energy(bb, bb)[0])
    big = jnp.concatenate([bb + 500.0 * i for i in range(30)], axis=1)  # 1800
    assert big.shape[1] > 1536
    e_big = float(backbone_energy(big, big)[0])  # lax.map chunked path
    # 3e-4: float32 accumulation order differs between the dense reduction
    # and the chunked lax.map sum (observed 1.02e-4 on some BLAS builds)
    np.testing.assert_allclose(e_big, 30 * e_small, rtol=3e-4)


def test_icode_residues_preserved(tmp_path):
    """Insertion-code residues (100 / 100A) stay distinct through parse ->
    backbone_trace -> write."""
    from alphafold2_tpu.utils import pdb as pdbio

    bb = np.asarray(_noisy_backbone(jax.random.key(8), L=2)[0]).reshape(2, 3, 3)
    s = pdbio.backbone_to_pdb("AG", bb)
    # give both residues resseq 100, second with icode A
    s = pdbio.dataclasses.replace(
        s,
        resseq=np.full(6, 100, np.int32),
        icode=np.asarray(["", "", "", "A", "A", "A"], "<U1"),
    )
    text = pdbio.to_pdb_string(s)
    reparsed = pdbio.parse_pdb(text)
    seq, coords, rows = reparsed.backbone_trace(return_indices=True)
    assert seq == "AG" and coords.shape == (2, 3, 3)
    assert list(reparsed.icode[rows[1]]) == ["A", "A", "A"]
