"""Tied-row attention under padding: exact mask semantics.

The reference FORBIDS padding under tied rows (alphafold2.py:147-149,
hard assert). This framework is exact instead: padded (row, position)
entries abstain from the shared logits, the r^-0.5 scale counts only
voting rows, and the softmax sees the shared column mask. These tests
prove the exactness property the reference can't offer: tied attention
on a padded batch equals tied attention on the cropped batch.
"""

import jax
import jax.numpy as jnp
import numpy as np

from alphafold2_tpu.ops.attention import Attention


def _attn(key, dim=16, heads=2, dim_head=8):
    mod = Attention(dim=dim, heads=heads, dim_head=dim_head, use_flash=False)
    x0 = jnp.zeros((2, 4, dim))
    params = mod.init(key, x0)
    return mod, params


def test_tied_column_padding_matches_cropped():
    # column padding (every row masks the same tail positions) — what MSA
    # length padding is. Padded entries are filled with huge garbage: if
    # anything leaks into the valid region, the comparison fails.
    b, r, n_valid, n_pad, dim = 2, 3, 8, 12, 16
    mod, params = _attn(jax.random.key(0), dim=dim)
    k1, k2 = jax.random.split(jax.random.key(1))
    x_valid = jax.random.normal(k1, (b, r, n_valid, dim))
    garbage = 1e3 * jax.random.normal(k2, (b, r, n_pad - n_valid, dim))
    x_pad = jnp.concatenate([x_valid, garbage], axis=2)
    mask = jnp.concatenate(
        [
            jnp.ones((b, r, n_valid), dtype=bool),
            jnp.zeros((b, r, n_pad - n_valid), dtype=bool),
        ],
        axis=2,
    )

    out_pad = mod.apply(
        params,
        x_pad.reshape(b * r, n_pad, dim),
        mask=mask.reshape(b * r, n_pad),
        tie_dim=r,
    ).reshape(b, r, n_pad, dim)
    # cropped oracle runs the unmasked branch (static r**-0.5 scale):
    # also proves the two branches agree when padding vanishes
    out_crop = mod.apply(
        params, x_valid.reshape(b * r, n_valid, dim), tie_dim=r
    ).reshape(b, r, n_valid, dim)

    np.testing.assert_allclose(
        out_pad[:, :, :n_valid], out_crop, rtol=1e-5, atol=1e-5
    )


def test_tied_fully_masked_rows_abstain():
    # depth padding: extra fully-masked MSA rows must not change the valid
    # rows' outputs (they abstain from the shared logits AND from the
    # row-count scale).
    b, r_valid, r_pad, n, dim = 2, 2, 4, 8, 16
    mod, params = _attn(jax.random.key(2), dim=dim)
    k1, k2 = jax.random.split(jax.random.key(3))
    x_valid = jax.random.normal(k1, (b, r_valid, n, dim))
    garbage = 1e3 * jax.random.normal(k2, (b, r_pad - r_valid, n, dim))
    x_pad = jnp.concatenate([x_valid, garbage], axis=1)
    mask = jnp.concatenate(
        [
            jnp.ones((b, r_valid, n), dtype=bool),
            jnp.zeros((b, r_pad - r_valid, n), dtype=bool),
        ],
        axis=1,
    )

    out_pad = mod.apply(
        params,
        x_pad.reshape(b * r_pad, n, dim),
        mask=mask.reshape(b * r_pad, n),
        tie_dim=r_pad,
    ).reshape(b, r_pad, n, dim)
    out_crop = mod.apply(
        params, x_valid.reshape(b * r_valid, n, dim), tie_dim=r_valid
    ).reshape(b, r_valid, n, dim)

    np.testing.assert_allclose(
        out_pad[:, :r_valid], out_crop, rtol=1e-5, atol=1e-5
    )


def test_tied_masked_grads_finite_and_padding_blind():
    # gradients flow through the masked tied path, and the grads w.r.t.
    # padded inputs are exactly zero (nothing downstream reads them)
    b, r, n_valid, n_pad, dim = 1, 2, 6, 8, 16
    mod, params = _attn(jax.random.key(4), dim=dim)
    x = jax.random.normal(jax.random.key(5), (b * r, n_pad, dim))
    mask = jnp.concatenate(
        [
            jnp.ones((b * r, n_valid), dtype=bool),
            jnp.zeros((b * r, n_pad - n_valid), dtype=bool),
        ],
        axis=1,
    )

    def loss(x):
        out = mod.apply(params, x, mask=mask, tie_dim=r)
        return jnp.sum(jnp.where(mask[..., None], out, 0.0) ** 2)

    g = jax.grad(loss)(x)
    assert np.all(np.isfinite(g))
    np.testing.assert_array_equal(np.asarray(g[:, n_valid:]), 0.0)


def test_tied_cross_attention_padding_matches_cropped():
    # tie_dim + broadcast context + masks on BOTH sides (the AxialAttention
    # tie_row_attn + context combination): query and kv sides are masked
    # independently, so context padding must also be exact
    b, r, n, nc_valid, nc_pad, dim = 2, 3, 6, 5, 8, 16
    mod, params = _attn(jax.random.key(10), dim=dim)
    kx, kc, kg = jax.random.split(jax.random.key(11), 3)
    x = jax.random.normal(kx, (b * r, n, dim))
    ctx_valid = jax.random.normal(kc, (b, nc_valid, dim))
    garbage = 1e3 * jax.random.normal(kg, (b, nc_pad - nc_valid, dim))
    ctx_pad = jnp.concatenate([ctx_valid, garbage], axis=1)
    # broadcast the per-sample context to every row, like AxialAttention does
    ctx_rows = jnp.repeat(ctx_pad, r, axis=0)
    cm = jnp.concatenate(
        [
            jnp.ones((b * r, nc_valid), dtype=bool),
            jnp.zeros((b * r, nc_pad - nc_valid), dtype=bool),
        ],
        axis=1,
    )
    mask = jnp.ones((b * r, n), dtype=bool)

    out_pad = mod.apply(
        params, x, context=ctx_rows, mask=mask, context_mask=cm, tie_dim=r
    )
    out_crop = mod.apply(
        params, x, context=jnp.repeat(ctx_valid, r, axis=0), tie_dim=r
    )
    np.testing.assert_allclose(out_pad, out_crop, rtol=1e-5, atol=1e-5)


def test_model_tied_rows_with_padded_msa_finite():
    # the flagship-bench combination: msa_tie_row_attn=True with a genuinely
    # padded MSA — previously the mask was silently dropped here
    from alphafold2_tpu.models import Alphafold2

    model = Alphafold2(
        dim=32, depth=2, heads=2, dim_head=16, max_seq_len=64,
        msa_tie_row_attn=True,
    )
    b, n, m, nm = 1, 16, 4, 16
    seq = jax.random.randint(jax.random.key(6), (b, n), 0, 21)
    msa = jax.random.randint(jax.random.key(7), (b, m, nm), 0, 21)
    mask = jnp.ones((b, n), dtype=bool)
    msa_mask = jnp.zeros((b, m, nm), dtype=bool)
    msa_mask = msa_mask.at[:, :3, :12].set(True)  # depth AND length padding
    params = model.init(
        jax.random.key(8), seq, msa, mask=mask, msa_mask=msa_mask
    )
    out = model.apply(params, seq, msa, mask=mask, msa_mask=msa_mask)
    assert out.shape == (b, n, n, 37)
    assert np.all(np.isfinite(out))
