"""Every examples/ script must actually run — they are the front door for
users switching from the reference package, so they rot loudly here
(EX_TINY=1 shrinks dims; each runs in its own process like a user would)."""

import glob
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
EXAMPLES = sorted(glob.glob(os.path.join(REPO, "examples", "*.py")))


def test_examples_exist():
    assert len(EXAMPLES) >= 5


@pytest.mark.parametrize("path", EXAMPLES, ids=[os.path.basename(p) for p in EXAMPLES])
def test_example_runs(path):
    env = dict(
        os.environ,
        EX_TINY="1",
        JAX_PLATFORMS="cpu",
        PALLAS_AXON_POOL_IPS="",
        XLA_FLAGS="--xla_force_host_platform_device_count=8",
    )
    proc = subprocess.run(
        [sys.executable, path],
        env=env,
        capture_output=True,
        text=True,
        timeout=600,
        cwd=REPO,
    )
    assert proc.returncode == 0, f"{path} failed:\n{proc.stdout}\n{proc.stderr}"
    assert proc.stdout.strip().endswith("ok"), proc.stdout
