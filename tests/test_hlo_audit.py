"""HLO-audit tests: collective-census parsing over synthetic HLO text,
the baseline-free structural rules (AF2A108-110), budget verdicts, exact
contract diffing with named per-collective deltas, and the baseline gate's
verdict machinery — all compile-free. The committed-baseline check and the
seeded-defect negative control (drop one shard_pair constraint, watch the
named all-gather delta fail the gate with no bench run) live in the slow
tier, mirroring CI's static-analysis job."""

import copy
import json
import os
import subprocess
import sys

import pytest

from alphafold2_tpu.analysis import budgets, hlo_audit

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# A hand-written optimized-HLO module exercising every parser edge: the
# num_partitions header attribute, an operand *reference* to an op named
# %all-gather.3 (must not count), an async -start/-done pair (must count
# once), and a tuple-shaped all-to-all (bytes summed over elements).
SYN_HLO = """\
HloModule jit_f, is_scheduled=true, num_partitions=8, \
entry_computation_layout={(f32[8,16]{1,0})->f32[64,16]{1,0}}

ENTRY %main (p0: f32[8,16]) -> f32[64,16] {
  %p0 = f32[8,16]{1,0} parameter(0)
  %all-gather.3 = f32[64,16]{1,0} all-gather(f32[8,16]{1,0} %p0), dimensions={0}
  %add = f32[64,16]{1,0} add(f32[64,16]{1,0} %all-gather.3, f32[64,16]{1,0} %all-gather.3)
  %ars = f32[64,16]{1,0} all-reduce-start(f32[64,16]{1,0} %add), to_apply=%sum
  %ard = f32[64,16]{1,0} all-reduce-done(f32[64,16]{1,0} %ars)
  %ata = (bf16[8,16]{1,0}, bf16[8,16]{1,0}) all-to-all(bf16[8,16]{1,0} %p0, bf16[8,16]{1,0} %p0)
  ROOT %out = f32[64,16]{1,0} copy(f32[64,16]{1,0} %ard)
}
"""


# --------------------------------------------------------------- parsing


def test_shape_bytes():
    assert hlo_audit.shape_bytes("f32[128,256]") == 128 * 256 * 4
    assert hlo_audit.shape_bytes("bf16[8]") == 16
    assert hlo_audit.shape_bytes("pred[4]") == 4
    assert hlo_audit.shape_bytes("f32[]") == 4  # scalar
    assert hlo_audit.shape_bytes("not-a-shape") == 0


def test_parse_collectives_on_synthetic_module():
    ops = hlo_audit.parse_collectives(SYN_HLO)
    assert [(o["kind"], o["bytes"]) for o in ops] == [
        ("all-gather", 64 * 16 * 4),   # gathered result shape
        ("all-reduce", 64 * 16 * 4),   # the -start half, counted once
        ("all-to-all", 2 * 8 * 16 * 2),  # tuple of two bf16[8,16]
    ]


def test_census_aggregates_and_sorts():
    census = hlo_audit.collective_census(SYN_HLO + SYN_HLO)
    assert list(census) == sorted(census)
    assert census["all-gather"] == {"count": 2, "bytes": 2 * 4096}
    assert census["all-to-all"]["count"] == 2


def test_operand_references_and_done_halves_not_counted():
    # only the three real collectives: the %all-gather.3 operand refs on
    # the add line and the all-reduce-done line contribute nothing
    assert sum(
        v["count"] for v in hlo_audit.collective_census(SYN_HLO).values()
    ) == 3


def test_num_partitions_header():
    assert hlo_audit.num_partitions(SYN_HLO) == 8
    assert hlo_audit.num_partitions("HloModule jit_f\n\nENTRY %main") == 1
    # the attribute can sit many KB into the header line — whole-text scan
    padded = "HloModule jit_f, layout={" + "x" * 5000 + "}, num_partitions=4"
    assert hlo_audit.num_partitions(padded) == 4


# ------------------------------------------------------ structural rules


def record(**kw):
    base = {
        "sharded": False, "num_partitions": 1, "collectives": {},
        "collective_count": 0, "comm_bytes": 0, "flops": 1e6,
        "program_bytes": 100, "hbm_budget_bytes": None,
        "budget": {"verdict": "no-data"},
    }
    base.update(kw)
    return base


def rules_of(findings):
    return sorted({f.rule for f in findings})


def test_collectives_in_single_device_target_flagged():
    rec = record(
        collectives={"all-gather": {"count": 2, "bytes": 64}},
        collective_count=2,
    )
    findings = hlo_audit.audit_record("t", rec)
    assert rules_of(findings) == ["AF2A109"]
    assert "all-gather x2" in findings[0].message


def test_sharded_target_with_zero_collectives_flagged():
    rec = record(sharded=True, num_partitions=4)
    findings = hlo_audit.audit_record("t", rec)
    assert rules_of(findings) == ["AF2A108"]
    assert "inert" in findings[0].message


def test_single_collective_blowup_flagged():
    rec = record(
        sharded=True, num_partitions=4, hbm_budget_bytes=1024,
        collectives={"all-gather": {"count": 1, "bytes": 4096}},
        collective_count=1,
    )
    findings = hlo_audit.audit_record(
        "t", rec, per_op=[{"kind": "all-gather", "bytes": 4096}]
    )
    assert rules_of(findings) == ["AF2A108"]
    assert "blowup" in findings[0].message


def test_over_budget_footprint_flagged():
    verdict = budgets.check_budget(2048, 1024)
    rec = record(
        sharded=True, num_partitions=4, hbm_budget_bytes=1024,
        collectives={"all-reduce": {"count": 1, "bytes": 8}},
        collective_count=1, program_bytes=2048, budget=verdict,
    )
    findings = hlo_audit.audit_record("t", rec)
    assert rules_of(findings) == ["AF2A110"]
    assert "2048" in findings[0].message


def test_healthy_sharded_record_is_clean():
    rec = record(
        sharded=True, num_partitions=4, hbm_budget_bytes=1 << 20,
        collectives={"all-reduce": {"count": 3, "bytes": 96}},
        collective_count=3, budget=budgets.check_budget(100, 1 << 20),
    )
    assert hlo_audit.audit_record(
        "t", rec, per_op=[{"kind": "all-reduce", "bytes": 32}] * 3
    ) == []


# --------------------------------------------------------------- budgets


def test_budget_verdicts():
    ok = budgets.check_budget(100, 1000)
    assert ok["verdict"] == "pass" and ok["headroom_frac"] == 0.9
    over = budgets.check_budget(2000, 1000)
    assert over["verdict"] == "over-budget"
    assert over["headroom_frac"] == -1.0
    assert budgets.check_budget(None, 1000)["verdict"] == "no-data"
    assert budgets.check_budget(100, None)["verdict"] == "no-data"


def test_format_budget_lines():
    assert "pass" in budgets.format_budget("t", budgets.check_budget(1, 2))
    assert "no-data" in budgets.format_budget(
        "t", budgets.check_budget(1, None)
    )


def test_device_hbm_env_override(monkeypatch):
    monkeypatch.setenv("AF2TPU_HBM_BYTES", str(16 << 30))
    assert budgets.device_hbm_bytes() == 16 << 30
    monkeypatch.delenv("AF2TPU_HBM_BYTES")
    # CPU test devices have no published HBM figure: explicit None
    assert budgets.device_hbm_bytes() is None


# ------------------------------------------------------------- diff/gate


def base_doc():
    return {
        "format": hlo_audit.FORMAT_VERSION, "jax_version": "0.0.test",
        "n_devices": 8, "platform": "cpu",
        "targets": {
            "t": {
                "sharded": True, "num_partitions": 8,
                "collectives": {
                    "all-gather": {"count": 20, "bytes": 890_000},
                    "all-reduce": {"count": 7, "bytes": 280},
                },
                "collective_count": 27, "comm_bytes": 890_280,
                "flops": 1000.0, "argument_bytes": 10, "output_bytes": 5,
                "temp_bytes": 1, "program_bytes": 1_000_000,
                "hbm_budget_bytes": 8 << 20,
                "budget": {"verdict": "pass"},
            }
        },
    }


def test_diff_names_the_dropped_collective_and_the_blowup():
    base, cur = base_doc(), base_doc()
    rec = cur["targets"]["t"]
    del rec["collectives"]["all-gather"]  # the dropped-shard_pair shape
    rec["comm_bytes"] = 280
    rec["program_bytes"] = 5_520_000
    rec["budget"] = {"verdict": "over-budget"}
    lines = hlo_audit.diff_hlo_contracts(base, cur)
    joined = "\n".join(lines)
    assert "t: all-gather count drift: 20 -> 0 (-20)" in lines
    assert "t: all-gather bytes drift: 890000 -> 0 (-890000)" in lines
    assert "program_bytes drift: 1000000 -> 5520000 (5.52x)" in joined
    assert "budget verdict drift: pass -> over-budget" in joined
    # the unchanged all-reduce census produces no line
    assert "all-reduce" not in joined


def test_diff_new_and_missing_targets_and_subset():
    base, cur = base_doc(), base_doc()
    cur["targets"]["extra"] = cur["targets"]["t"]
    assert any(
        "extra: NEW TARGET" in ln
        for ln in hlo_audit.diff_hlo_contracts(base, cur)
    )
    only_new = {**base_doc(), "targets": {"extra": base_doc()["targets"]["t"]}}
    full = hlo_audit.diff_hlo_contracts(base, only_new)
    assert any("t: missing from current audit" in ln for ln in full)
    # a --targets subset run must not read unaudited targets as removed
    sub = hlo_audit.diff_hlo_contracts(base, only_new, subset=True)
    assert not any("missing" in ln for ln in sub)


def test_check_against_verdicts(tmp_path):
    path = tmp_path / "hlo_contracts.json"
    assert hlo_audit.check_against(
        str(path), base_doc()
    )["verdict"] == "missing-baseline"

    path.write_text(json.dumps(base_doc()))
    assert hlo_audit.check_against(str(path), base_doc()) == {
        "verdict": "pass", "drift": [],
    }

    stale = base_doc()
    stale["jax_version"] = "9.9.9"
    res = hlo_audit.check_against(str(path), stale)
    assert res["verdict"] == "stale-baseline"
    assert "RECOMPILE KEY jax_version" in res["reason"]

    drifted = copy.deepcopy(base_doc())
    drifted["targets"]["t"]["collectives"]["all-gather"]["count"] = 21
    res = hlo_audit.check_against(str(path), drifted)
    assert res["verdict"] == "drift"
    assert any("all-gather count drift" in ln for ln in res["drift"])


def test_cli_unknown_target_is_usage_error(capsys):
    assert hlo_audit.main(["--check", "--targets", "no_such"]) == 2
    assert "unknown hlo target" in capsys.readouterr().err


# ------------------------------------------------- real targets (slow tier)


@pytest.mark.slow
def test_committed_hlo_baseline_holds():
    """The shipped targets compile with zero structural findings and match
    the committed hlo_contracts.json — the CI static-analysis job's
    in-suite twin (stale-baseline accepted, exactly like the CLI, when the
    environment's recompile keys differ)."""
    doc, findings = hlo_audit.audit_hlo()
    assert findings == [], [f.format() for f in findings]
    result = hlo_audit.check_against(hlo_audit.DEFAULT_BASELINE, doc)
    assert result["verdict"] in ("pass", "stale-baseline"), result


@pytest.mark.slow
def test_seeded_defect_fails_statically():
    """The acceptance criterion: dropping a single shard_pair constraint
    (AF2TPU_AUDIT_DROP_SHARD_PAIR, parallel/sharding.py) must fail the
    gate with a *named* all-gather census delta — caught at compile time,
    no bench run."""
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "AF2TPU_AUDIT_DROP_SHARD_PAIR": "1"}
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    proc = subprocess.run(
        [sys.executable, "-m", "alphafold2_tpu.analysis.hlo_audit",
         "--check", "--targets", "serve_fwd_long"],
        capture_output=True, text=True, timeout=900, env=env, cwd=REPO,
    )
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "all-gather count drift" in proc.stdout
    assert "AF2A107" in proc.stdout  # contract drift
    assert "AF2A108" in proc.stdout  # replicated: zero collectives
    assert "AF2A110" in proc.stdout  # replication blew the HBM budget
