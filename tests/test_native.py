"""Native data-loader runtime tests: differential vs the jnp bucketization
oracle, batch-schema/determinism properties, and the threaded prefetch queue.
Skipped wholesale when the shared library hasn't been built
(``make -C native``)."""

import os

import numpy as np
import pytest

from alphafold2_tpu.config import DataConfig
from alphafold2_tpu.data import native

# Applied per-test (NOT module-wide): the tsan stress test builds its own
# binary and must run even when libaf2data.so hasn't been built yet.
needs_lib = pytest.mark.skipif(
    not native.available(), reason="native library not built (make -C native)"
)


def _cfg(**kw):
    base = dict(crop_len=24, msa_depth=2, msa_len=16, batch_size=2,
                min_len_filter=8)
    base.update(kw)
    return DataConfig(**base)


@needs_lib
def test_bucketize_matches_jnp_oracle():
    from alphafold2_tpu.utils.structure import get_bucketed_distance_matrix

    rng = np.random.default_rng(0)
    coords = rng.normal(scale=8.0, size=(48, 3)).astype(np.float32)
    mask = np.ones(48, bool)
    mask[40:] = False
    got = native.bucketize_distances(coords, mask)
    want = np.asarray(get_bucketed_distance_matrix(coords[None], mask[None]))[0]
    # float assoc. differences may shift distances sitting exactly on a bin
    # edge by one bucket; require exact agreement on (nearly) all entries
    mismatch = (got != want).mean()
    assert mismatch < 1e-3, f"mismatch fraction {mismatch}"
    assert (got[~mask[:, None] | ~mask[None, :]] == -100).all()


@needs_lib
def test_synthesize_batch_schema_and_determinism():
    cfg = _cfg()
    b1 = native.synthesize_batch(cfg, seed=7)
    b2 = native.synthesize_batch(cfg, seed=7)
    b3 = native.synthesize_batch(cfg, seed=8)
    assert b1["seq"].shape == (2, 24) and b1["msa"].shape == (2, 2, 16)
    assert b1["coords"].shape == (2, 24, 3) and b1["backbone"].shape == (2, 72, 3)
    for k in ("seq", "msa", "coords"):
        assert np.array_equal(b1[k], b2[k]), k  # same seed -> same batch
    assert not np.array_equal(b1["seq"], b3["seq"])  # different seed

    # masked-out tail is padding; valid region is in-vocab
    for b in range(2):
        n = int(b1["mask"][b].sum())
        assert (b1["seq"][b, :n] < 20).all()
        assert (b1["seq"][b, n:] == 20).all()
        # consecutive CA distance ~3.8A in the valid region
        ca = b1["coords"][b, :n]
        steps = np.linalg.norm(np.diff(ca, axis=0), axis=-1)
        assert np.allclose(steps, 3.8, atol=0.2)
        # N/CA/C backbone triplets bracket each CA
        bb = b1["backbone"][b, : n * 3].reshape(n, 3, 3)
        assert np.allclose(bb[:, 1], ca, atol=1e-6)
        assert (np.linalg.norm(bb[:, 0] - ca, axis=-1) < 2.5).all()


@needs_lib
def test_prefetch_loader_streams_batches():
    cfg = _cfg()
    with native.NativeSyntheticLoader(cfg, seed=0, num_workers=2,
                                      queue_capacity=3) as loader:
        seqs = []
        for _ in range(5):
            batch = next(loader)
            assert batch["labels"].shape == (2, 24, 24)
            # labels agree with a host recomputation from the same coords
            want = native.bucketize_distances(batch["coords"][0], batch["mask"][0])
            assert np.array_equal(batch["labels"][0], want)
            seqs.append(batch["seq"].copy())
        # worker seeds advance: batches are not all identical
        assert any(not np.array_equal(seqs[0], s) for s in seqs[1:])


@needs_lib
def test_train_step_consumes_native_batches():
    import jax

    from alphafold2_tpu.config import Config, ModelConfig, TrainConfig
    from alphafold2_tpu.data.pipeline import make_dataset
    from alphafold2_tpu.train.loop import (
        build_model, device_put_batch, init_state, make_train_step,
    )

    cfg = Config(
        model=ModelConfig(dim=32, depth=1, heads=2, dim_head=16,
                          max_seq_len=64, bfloat16=False),
        data=_cfg(crop_len=16, msa_len=16, source="native"),
        train=TrainConfig(gradient_accumulate_every=1, warmup_steps=2),
    )
    loader = make_dataset(cfg.data, seed=0)
    assert isinstance(loader, native.NativeSyntheticLoader)
    with loader:
        batch = next(loader)
        model = build_model(cfg)
        state = init_state(cfg, model, batch)
        step = make_train_step(model)
        state, metrics = step(state, device_put_batch(batch), jax.random.key(0))
        assert np.isfinite(float(metrics["loss"]))
        assert bool(metrics["grads_ok"])


@needs_lib
def test_loader_stream_deterministic_across_worker_counts():
    # same seed, different worker counts -> byte-identical batch stream
    # (workers claim sequential indices; consumer pops in index order)
    def take(n_workers, n_batches=4):
        with native.NativeSyntheticLoader(_cfg(), seed=3,
                                          num_workers=n_workers) as ld:
            return [next(ld) for _ in range(n_batches)]

    a, b = take(1), take(3)
    for ba, bb in zip(a, b):
        for k in ("seq", "msa", "coords", "labels"):
            assert np.array_equal(ba[k], bb[k]), k


@needs_lib
def test_loader_close_idempotent():
    loader = native.NativeSyntheticLoader(_cfg(), seed=1, num_workers=1)
    next(loader)
    loader.close()
    loader.close()  # double-close must not crash
    with pytest.raises(StopIteration):
        next(loader)  # closed loader must not touch the C side
    assert loader.queue_size() == 0


def test_tsan_stress_clean():
    # race-detection tier (SURVEY.md S5.2): build the loader + stress harness
    # under ThreadSanitizer and run it; any data race in dataloader.cc's
    # worker/queue machinery fails this test. Skipped where tsan is absent.
    import subprocess

    native_dir = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "native")
    build = subprocess.run(
        ["make", "-C", native_dir, "loader_stress_tsan"],
        capture_output=True, text=True,
    )
    if build.returncode != 0:
        # skip ONLY for sanitizer absence; a compile error in the loader or
        # harness must FAIL, not silently disable the race tier
        sanitizer_missing = any(
            sig in build.stderr
            for sig in ("fsanitize=thread", "libtsan", "tsan_interface")
        )
        if sanitizer_missing:
            pytest.skip(f"tsan unavailable: {build.stderr[-200:]}")
        pytest.fail(f"tsan harness build failed:\n{build.stderr[-2000:]}")
    run = subprocess.run(
        [os.path.join(native_dir, "loader_stress_tsan"), "2"],
        capture_output=True, text=True, timeout=300,
    )
    assert run.returncode == 0, (run.stdout[-500:], run.stderr[-2000:])
    assert "loader_stress ok" in run.stdout


@needs_lib
def test_min_len_exceeds_crop_len_is_safe():
    # both sources clamp min_len to the crop: full-length chains, no error
    cfg = _cfg(crop_len=8, min_len_filter=16)
    b = native.synthesize_batch(cfg, seed=0)
    assert b["mask"].all()  # chain fills the whole crop
    assert (b["seq"] < 20).all()

    from alphafold2_tpu.data.pipeline import SyntheticDataset

    nb = next(iter(SyntheticDataset(cfg, seed=0)))
    assert nb["mask"].all()
