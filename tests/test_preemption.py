"""Preemption safety: SIGTERM mid-training checkpoints and exits cleanly;
a relaunch resumes from the saved step (SURVEY.md S5.3 — elastic-recovery
capability the reference lacks entirely). Driven as a real subprocess so
the signal path is the production one."""

import json
import os
import signal
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

ARGS = [
    "train.num_steps=100000", "train.log_every=1", "train.checkpoint_every=50000",
    "data.crop_len=12", "data.min_len_filter=8", "data.msa_len=8",
    "data.msa_depth=2", "model.dim=32", "model.depth=1", "model.heads=2",
    "model.dim_head=16", "model.max_seq_len=24", "model.bfloat16=false",
    "train.gradient_accumulate_every=1",
]


def _launch(ckpt_dir, extra=()):
    env = dict(os.environ, AF2TPU_PLATFORM="cpu")
    return subprocess.Popen(
        [sys.executable, os.path.join(REPO, "train_pre.py"),
         f"train.checkpoint_dir={ckpt_dir}", *ARGS, *extra],
        cwd=REPO, env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True,
    )


def _wait_for_steps(proc, metrics_path, n, timeout=240):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if os.path.exists(metrics_path):
            with open(metrics_path) as f:
                lines = f.readlines()
            if len(lines) >= n:
                return [json.loads(l) for l in lines]
        if proc.poll() is not None:
            raise AssertionError(
                f"trainer exited early: {proc.stdout.read()[-2000:]}"
            )
        time.sleep(0.5)
    raise AssertionError("timed out waiting for training steps")


@pytest.mark.slow
def test_sigterm_checkpoints_and_resumes(tmp_path):
    ckpt = str(tmp_path / "ckpt")
    metrics = os.path.join(ckpt, "metrics.jsonl")

    proc = _launch(ckpt)
    try:
        _wait_for_steps(proc, metrics, 3)
        proc.send_signal(signal.SIGTERM)
        out, _ = proc.communicate(timeout=240)
    finally:
        if proc.poll() is None:
            proc.kill()
    assert proc.returncode == 0, out[-2000:]
    assert "preempted" in out

    steps = [d for d in os.listdir(ckpt) if d.isdigit()]
    assert steps, f"no checkpoint written: {os.listdir(ckpt)}"
    saved = max(int(s) for s in steps)
    assert 0 < saved < 100000

    # relaunch: must resume from the saved step, not step 0
    proc2 = _launch(ckpt)
    try:
        records = _wait_for_steps(proc2, metrics, len(open(metrics).readlines()) + 1)
    finally:
        proc2.send_signal(signal.SIGTERM)
        try:
            proc2.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            proc2.kill()
    resumed_steps = [r["step"] for r in records if "loss" in r]
    assert any(s >= saved for s in resumed_steps), (saved, resumed_steps[-5:])
