"""Preemption safety: SIGTERM mid-training checkpoints and exits cleanly;
a relaunch resumes from the saved step (SURVEY.md S5.3 — elastic-recovery
capability the reference lacks entirely). Driven as a real subprocess so
the signal path is the production one."""

import json
import os
import signal
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

ARGS = [
    "train.num_steps=100000", "train.log_every=1", "train.checkpoint_every=50000",
    "data.crop_len=12", "data.min_len_filter=8", "data.msa_len=8",
    "data.msa_depth=2", "model.dim=32", "model.depth=1", "model.heads=2",
    "model.dim_head=16", "model.max_seq_len=24", "model.bfloat16=false",
    "train.gradient_accumulate_every=1",
]


# under parallel-suite load the subprocess's cold jax import + first-step
# compile can take minutes; the deadline is generous (and overridable for
# slower CI machines) because a timeout here is a flake, not a signal
WAIT_S = float(os.environ.get("AF2TPU_TEST_PREEMPT_TIMEOUT_S", "420"))


def _launch(ckpt_dir, extra=()):
    # isolate the child from harness-level AF2TPU_* knobs (metrics
    # redirection, telemetry, platform overrides) — an outer CI exporting
    # AF2TPU_METRICS_DIR would silently move the metrics.jsonl this test
    # polls, which reads as "trainer never stepped"
    env = {k: v for k, v in os.environ.items()
           if not k.startswith("AF2TPU_")}
    env["AF2TPU_PLATFORM"] = "cpu"
    return subprocess.Popen(
        [sys.executable, os.path.join(REPO, "train_pre.py"),
         f"train.checkpoint_dir={ckpt_dir}", *ARGS, *extra],
        cwd=REPO, env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True,
    )


def _parse_lines(lines):
    # the trainer appends lines while this poller reads: a torn trailing
    # line is normal, not corruption — parse what's complete, drop the rest
    out = []
    for l in lines:
        try:
            out.append(json.loads(l))
        except json.JSONDecodeError:
            break
    return out


def _wait_for_steps(proc, metrics_path, n, timeout=WAIT_S):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if os.path.exists(metrics_path):
            with open(metrics_path) as f:
                records = _parse_lines(f.readlines())
            if len(records) >= n:
                return records
        if proc.poll() is not None:
            raise AssertionError(
                f"trainer exited early: {proc.stdout.read()[-2000:]}"
            )
        time.sleep(0.5)
    raise AssertionError("timed out waiting for training steps")


@pytest.mark.slow
def test_sigterm_checkpoints_and_resumes(tmp_path):
    ckpt = str(tmp_path / "ckpt")
    metrics = os.path.join(ckpt, "metrics.jsonl")

    proc = _launch(ckpt)
    try:
        _wait_for_steps(proc, metrics, 3)
        proc.send_signal(signal.SIGTERM)
        out, _ = proc.communicate(timeout=WAIT_S)
    finally:
        if proc.poll() is None:
            proc.kill()
    assert proc.returncode == 0, out[-2000:]
    assert "preempted" in out

    steps = [d for d in os.listdir(ckpt) if d.isdigit()]
    assert steps, f"no checkpoint written: {os.listdir(ckpt)}"
    saved = max(int(s) for s in steps)
    assert 0 < saved < 100000

    # relaunch: must resume from the saved step, not step 0
    with open(metrics) as f:
        n_before = len(_parse_lines(f.readlines()))
    proc2 = _launch(ckpt)
    try:
        records = _wait_for_steps(proc2, metrics, n_before + 1)
    finally:
        proc2.send_signal(signal.SIGTERM)
        try:
            proc2.communicate(timeout=WAIT_S)
        except subprocess.TimeoutExpired:
            proc2.kill()
    resumed_steps = [r["step"] for r in records if "loss" in r]
    assert any(s >= saved for s in resumed_steps), (saved, resumed_steps[-5:])
