"""tiny_init_state invariant: initializing at tiny data shapes produces the
BIT-IDENTICAL TrainState to full-size init.

Param shapes (and flax's shape-driven initializer values + rng consumption
order) depend only on the model config, never on crop/MSA batch shapes —
this is what lets every driver skip the full-size init compile (measured
1348s at crop 256 on CPU, vs 49s for the training-step compile itself).
"""

import jax
import numpy as np
import pytest

from alphafold2_tpu.config import Config, DataConfig, ModelConfig, TrainConfig
from alphafold2_tpu.data.pipeline import SyntheticDataset
from alphafold2_tpu.train.loop import (
    build_model,
    init_state,
    tiny_batch_like,
    tiny_init_state,
)


def _cfg(**data_kw):
    return Config(
        model=ModelConfig(
            dim=32, depth=1, heads=2, dim_head=16, max_seq_len=128,
            msa_tie_row_attn=True,
        ),
        data=DataConfig(**data_kw),
        train=TrainConfig(),
    )


def _assert_identical(a, b):
    la, lb = jax.tree.leaves(a.params), jax.tree.leaves(b.params)
    assert len(la) == len(lb)
    assert all(np.array_equal(x, y) for x, y in zip(la, lb))


def test_tiny_init_matches_full_init():
    cfg = _cfg(crop_len=48, msa_depth=4, msa_len=48, batch_size=2,
               min_len_filter=48)
    batch = next(iter(SyntheticDataset(cfg.data, seed=0)))
    model = build_model(cfg)
    full = init_state(cfg, model, batch)
    _assert_identical(full, tiny_init_state(cfg, model, batch))  # sliced
    _assert_identical(full, tiny_init_state(cfg, model))  # synthetic


def test_tiny_init_preserves_plm_feature_structure():
    # the embedds width sizes embedd_project's kernel: the sliced batch must
    # carry it through (a synthetic rebuild could use the wrong provider dim)
    cfg = _cfg(crop_len=32, msa_depth=2, msa_len=32, batch_size=1,
               min_len_filter=32, features="plm")
    from alphafold2_tpu.train.loop import apply_features

    batch = next(apply_features(iter(SyntheticDataset(cfg.data, seed=0)), cfg))
    assert "embedds" in batch and batch.get("msa") is None
    model = build_model(cfg)
    full = init_state(cfg, model, batch)
    _assert_identical(full, tiny_init_state(cfg, model, batch))
    tiny = tiny_batch_like(batch)
    assert tiny["embedds"].shape[-1] == batch["embedds"].shape[-1]


@pytest.mark.slow
def test_tiny_init_matches_full_init_end2end():
    # the end2end drivers init from tiny_batch_like too: the structure half
    # (MDS realization, sidechain lift, SE3 refiner) must also be free of
    # input-shape-dependent params / rng draws
    from alphafold2_tpu.train.end2end import End2EndModel, init_end2end_state

    cfg = _cfg(crop_len=24, msa_depth=2, msa_len=24, batch_size=1,
               min_len_filter=24)
    batch = next(iter(SyntheticDataset(cfg.data, seed=0)))
    model = End2EndModel(
        dim=32, depth=1, heads=2, dim_head=16, max_seq_len=128, mds_iters=4,
    )
    full = init_end2end_state(cfg, model, batch)
    tiny = init_end2end_state(cfg, model, tiny_batch_like(batch))
    _assert_identical(full, tiny)


@pytest.mark.slow
def test_tiny_init_matches_full_init_templates():
    # bench_suite config_4 inits at tiny template shapes inline; this pins
    # the invariant that run relies on: the template embedder (with and
    # without the SE(3) sidechain colorer) has no input-shape-dependent
    # params or rng draws, so tiny-shape init is bit-identical (ADVICE r2)
    import jax.numpy as jnp

    from alphafold2_tpu.models import Alphafold2

    crop, msa_d, T, tn, tT = 24, 3, 3, 12, 2
    for use_se3 in (False, True):
        model = Alphafold2(
            dim=32, depth=1, heads=2, dim_head=16, max_seq_len=64,
            msa_tie_row_attn=True, template_attn_depth=1,
            use_se3_template_embedder=use_se3,
        )
        k = jax.random.key(7)
        seq = jax.random.randint(jax.random.fold_in(k, 1), (1, crop), 0, 21)
        msa = jax.random.randint(
            jax.random.fold_in(k, 2), (1, msa_d, crop), 0, 21
        )
        t_seq = jax.random.randint(
            jax.random.fold_in(k, 3), (1, T, crop), 0, 21
        )
        t_coors = jax.random.normal(
            jax.random.fold_in(k, 4), (1, T, crop, 3)
        ) * 10
        full = model.init(
            k, seq, msa,
            mask=jnp.ones((1, crop), bool),
            msa_mask=jnp.ones((1, msa_d, crop), bool),
            templates_seq=t_seq, templates_coors=t_coors,
            templates_mask=jnp.ones((1, T, crop), bool),
        )
        tiny = model.init(
            k, seq[:, :tn], msa[:, :2, :tn],
            mask=jnp.ones((1, tn), bool),
            msa_mask=jnp.ones((1, 2, tn), bool),
            templates_seq=t_seq[:, :tT, :tn],
            templates_coors=t_coors[:, :tT, :tn],
            templates_mask=jnp.ones((1, tT, tn), bool),
        )
        lf, lt = jax.tree.leaves(full), jax.tree.leaves(tiny)
        assert len(lf) == len(lt), f"use_se3={use_se3}"
        assert all(np.array_equal(a, b) for a, b in zip(lf, lt)), (
            f"use_se3={use_se3}"
        )


def test_tiny_batch_like_shapes():
    batch = {
        "seq": np.zeros((2, 64), np.int32),
        "mask": np.ones((2, 64), bool),
        "msa": np.zeros((2, 8, 64), np.int32),
        "msa_mask": np.ones((2, 8, 64), bool),
        "coords": np.zeros((2, 64, 3)),  # non-feature keys are dropped
    }
    tiny = tiny_batch_like(batch)
    assert tiny["seq"].shape == (1, 16)
    assert tiny["msa"].shape == (1, 2, 16)
    assert "coords" not in tiny
