"""Workload capture & deterministic replay plane (observe/workload.py).

The load-bearing contracts: the scrubbed default log leaks neither raw
sequences nor caller-controlled metadata (parent hints are one-way
hashed, error text never recorded) while keeping scan families visible
via edit summaries; ``build_replay`` reproduces timing/warp/scale
semantics deterministically; ``synthetic_diurnal`` is seeded; the
FlightRecorder's incident dumps carry the scrubbed workload tail; and a
combined affinity + dedup run reconstructs every lifecycle with the
recorder seeing a submit for every resolve."""

import json
import os

import numpy as np
import pytest

from alphafold2_tpu.config import (
    Config,
    DataConfig,
    ModelConfig,
    ServeConfig,
)
from alphafold2_tpu.observe import EventCounters, FlightRecorder, Tracer
from alphafold2_tpu.observe.tracectx import trace_completeness
from alphafold2_tpu.observe.workload import (
    WorkloadRecorder,
    build_replay,
    derivation_fingerprint,
    load_workload,
    replayable_reason,
    synthetic_diurnal,
)
from alphafold2_tpu.serve import (
    AsyncServeFrontend,
    ServeRequest,
    ServeResult,
)

SECRET = "AXON_API_TOKEN_hunter2"
SEQUENCE = "MKVLITHDSAGE"


def _cfg(buckets=(8, 16), max_batch=4, **serve_kw):
    serve_kw.setdefault("mds_iters", 10)
    return Config(
        model=ModelConfig(dim=32, depth=1, heads=2, dim_head=16,
                          max_seq_len=3 * max(buckets), bfloat16=False),
        data=DataConfig(msa_depth=2),
        serve=ServeConfig(buckets=buckets, max_batch=max_batch, **serve_kw),
    )


class FakeClock:
    def __init__(self):
        self.now = 100.0

    def __call__(self):
        return self.now

    def advance(self, dt):
        self.now += dt


class TracingFakeEngine:
    def __init__(self, cfg):
        self.cfg = cfg
        self.buckets = cfg.serve.buckets
        self.max_batch = cfg.serve.max_batch
        self.mesh_desc = None
        self.counters = EventCounters()
        self.tracer = Tracer(enabled=True)
        self.dispatched = []

    def batch_for(self, bucket):
        return self.max_batch

    def dispatch_batch(self, bucket, reqs):
        self.dispatched.append((bucket, [r.seq for r in reqs]))
        return [
            ServeResult(
                seq=r.seq, bucket=bucket,
                atom14=np.zeros((len(r.seq), 14, 3), np.float32),
                latency_s=1e-3,
                trace_id=r.trace.trace_id if r.trace else None,
            )
            for r in reqs
        ]

    def retry_bucket(self, bucket):
        i = self.buckets.index(bucket)
        return self.buckets[i + 1] if i + 1 < len(self.buckets) else None


def _frontend(**serve_kw):
    serve_kw.setdefault("dwell_ms", 50.0)
    eng = TracingFakeEngine(_cfg(**serve_kw))
    clock = FakeClock()
    fe = AsyncServeFrontend(eng, clock=clock, start=False)
    return fe, eng, clock


def _recorded(path=None, record_raw=False, **serve_kw):
    fe, eng, clock = _frontend(**serve_kw)
    rec = WorkloadRecorder(
        path=path, record_raw=record_raw,
        buckets=eng.buckets, msa_depth=2, clock=clock,
    )
    fe.add_submit_observer(rec.on_submit)
    fe.add_observer(rec.observe)
    return fe, eng, clock, rec


# -------------------------------------------------------------- recording


def test_recorder_sees_submit_and_resolve_linked_by_trace():
    fe, eng, clock, rec = _recorded()
    req = ServeRequest("ACDEFG", seed=3, priority=1, deadline_s=5.0)
    h1 = fe.submit(req, priority=1)
    clock.advance(0.25)
    h2 = fe.submit("MKVLIT")
    fe.pump()
    assert h1.result(0).ok and h2.result(0).ok
    events = rec.events()
    submits = [e for e in events if e["kind"] == "submit"]
    resolves = [e for e in events if e["kind"] == "resolve"]
    assert len(submits) == 2 and len(resolves) == 2
    first = submits[0]
    assert first["trace"] == req.trace.trace_id
    assert first["t"] == 0.0  # stream t0 anchors at the first arrival
    assert submits[1]["t"] == pytest.approx(0.25)
    assert first["len"] == 6 and first["seed"] == 3
    assert first["priority"] == 1 and first["deadline_s"] == 5.0
    assert first["fp"] == derivation_fingerprint("ACDEFG", 8, 2, 3)
    resolved_traces = {e["trace"] for e in resolves}
    assert resolved_traces == {s["trace"] for s in submits}
    assert all(e["status"] == "ok" for e in resolves)


def test_scrubbed_log_leaks_no_sequence_and_no_planted_secret(tmp_path):
    # satellite 6 negative control: a secret-shaped parent hint and a
    # real sequence go in; neither literal may reach the scrubbed JSONL
    log = tmp_path / "wl.jsonl"
    fe, eng, clock, rec = _recorded(path=str(log))
    fe.submit(ServeRequest(SEQUENCE, parent_id=SECRET))
    fe.pump()
    rec.close()
    text = log.read_text()
    assert SECRET not in text
    assert "hunter2" not in text
    assert SEQUENCE not in text
    # the hint survives as a hash: same secret -> same label, so
    # affinity semantics are preserved without the content
    ev = json.loads(text.splitlines()[0])
    assert ev["kind"] == "submit" and len(ev["parent"]) == 16


def test_record_raw_opt_in_adds_sequence_but_still_hashes_parent(tmp_path):
    log = tmp_path / "wl_raw.jsonl"
    fe, eng, clock, rec = _recorded(path=str(log), record_raw=True)
    fe.submit(ServeRequest(SEQUENCE, parent_id=SECRET))
    fe.pump()
    rec.close()
    text = log.read_text()
    assert SEQUENCE in text  # the opt-in's whole point
    assert SECRET not in text  # parent hints are hashed EVEN with raw


def test_resolve_events_never_carry_error_text():
    rec = WorkloadRecorder()
    boom = ServeResult(seq="ACDEFG", bucket=8, status="error",
                       error=f"dispatch blew up on {SECRET}",
                       trace_id="t-1", latency_s=0.5)
    rec.observe(boom, priority=0)
    (ev,) = rec.events()
    assert ev["status"] == "error" and ev["trace"] == "t-1"
    assert SECRET not in json.dumps(ev)


def test_edit_summary_keeps_scan_families_visible_when_scrubbed():
    fe, eng, clock, rec = _recorded()
    parent = "ACDEFGHIKLMN"
    mutant = parent[:5] + "W" + parent[6:]
    fe.submit(ServeRequest(parent, seed=1))
    fe.submit(ServeRequest(mutant, seed=1))
    fe.pump()
    submits = [e for e in rec.events() if e["kind"] == "submit"]
    assert "edits" not in submits[0]
    assert submits[1]["edits"] == 1 and submits[1]["edit_pos"] == [5]
    assert submits[1]["parent_fp"] == submits[0]["fp"]
    assert "seq" not in submits[1]  # family visible WITHOUT content


def test_recorder_never_raises_into_the_serving_path():
    rec = WorkloadRecorder()
    rec.observe(object(), priority=0)  # wrong shape entirely
    assert rec.errors == 1 and rec.events() == []


def test_tail_and_family_by_trace():
    fe, eng, clock, rec = _recorded(affinity_batching=True)
    for i in range(12):
        fe.submit(ServeRequest("ACDEFG"[: 4 + i % 3] + "GG", seed=i,
                               parent_id="famX"))
    fe.pump()
    assert len(rec.tail(5)) == 5
    fams = rec.family_by_trace()
    assert len(fams) == 12
    hashed = {v for v in fams.values() if v}
    assert hashed and all(len(v) == 16 for v in hashed)
    assert "hint:famX" not in hashed  # family labels are hashed too


# ----------------------------------------------------------------- replay


def test_load_workload_tolerates_torn_tail(tmp_path):
    log = tmp_path / "torn.jsonl"
    evs = synthetic_diurnal(seed=1, requests=4, buckets=(12, 16))
    lines = [json.dumps(e) for e in evs]
    lines.append(json.dumps({"v": 1, "kind": "summary", "requests": 4}))
    log.write_text("\n".join(lines) + '\n{"v": 1, "kind": "sub')
    loaded = load_workload(str(log))
    assert len(loaded["submits"]) == 4
    assert loaded["summary"]["requests"] == 4
    offsets = [e["t"] for e in loaded["submits"]]
    assert offsets == sorted(offsets)


def test_build_replay_warp_and_scale_semantics():
    evs = synthetic_diurnal(seed=2, requests=6, buckets=(12, 16))
    base = build_replay(evs)
    warped = build_replay(evs, time_warp=2.0, load_scale=3)
    assert len(base) == 6 and len(warped) == 18
    assert [t for t, _ in warped] == sorted(t for t, _ in warped)
    base_off = sorted(t for t, _ in base)
    warp_off = sorted(set(t for t, _ in warped))
    assert warp_off == pytest.approx([t / 2.0 for t in base_off])
    # copies are real new work: same seq, distinct seeds
    by_seq = {}
    for _, req in warped:
        by_seq.setdefault(req.seq, set()).add(req.seed)
    for seq, seeds in by_seq.items():
        originals = {r.seed for _, r in base if r.seq == seq}
        assert len(seeds) == 3 * len(originals)


def test_build_replay_rejects_scrubbed_logs_and_bad_args():
    evs = synthetic_diurnal(seed=3, requests=3, buckets=(12, 16))
    scrubbed = [{k: v for k, v in e.items() if k != "seq"} for e in evs]
    assert replayable_reason(evs) is None
    assert "no raw sequence" in replayable_reason(scrubbed)
    assert "no submit events" in replayable_reason([])
    with pytest.raises(ValueError, match="no raw sequence"):
        build_replay(scrubbed)
    with pytest.raises(ValueError, match="time_warp"):
        build_replay(evs, time_warp=0.0)
    with pytest.raises(ValueError, match="load_scale"):
        build_replay(evs, load_scale=0)


def test_synthetic_diurnal_is_seeded_and_carries_scan_traffic():
    a = synthetic_diurnal(seed=7, requests=40)
    b = synthetic_diurnal(seed=7, requests=40)
    assert a == b  # byte-for-byte deterministic per seed
    assert a != synthetic_diurnal(seed=8, requests=40)
    keys = [(e["seq"], e["seed"]) for e in a]
    assert len(set(keys)) < len(keys)  # dup traffic present
    assert any("parent" in e for e in a)  # mutant families present
    assert all(e["bucket"] >= e["len"] for e in a)
    offsets = [e["t"] for e in a]
    assert offsets == sorted(offsets) and offsets[0] > 0


# ----------------------------------------------- flightrec workload tail


def test_flightrec_dump_includes_scrubbed_workload_tail(tmp_path):
    fe, eng, clock, rec = _recorded()
    fe.submit(ServeRequest(SEQUENCE, parent_id=SECRET))
    clock.advance(0.051)
    fe.pump()
    fr = FlightRecorder(directory=str(tmp_path)).attach_workload(rec.tail)
    path = fr.dump("test_incident")
    doc = json.loads(open(path).read())
    tail = doc["workload_tail"]
    assert [e["kind"] for e in tail] == ["submit", "resolve"]
    blob = json.dumps(tail)
    assert SECRET not in blob and SEQUENCE not in blob


def test_flightrec_dump_without_workload_has_no_tail_key(tmp_path):
    fr = FlightRecorder(directory=str(tmp_path))
    doc = json.loads(open(fr.dump("no_tail")).read())
    assert "workload_tail" not in doc


# ------------------------------------- combined lifecycles (satellite 3)


def test_affinity_dedup_and_admission_reconstruct_completely():
    """Affinity batching + duplicate dedup joins + plain admission in one
    run: every lifecycle reconstructs to a complete trace AND the workload
    recorder holds a submit event for every resolve it saw."""
    fe, eng, clock, rec = _recorded(
        affinity_batching=True, dwell_ms=50.0, max_batch=4
    )
    parent = "ACDEFGHIKLMN"
    muts = [parent[:p] + "W" + parent[p + 1:] for p in (2, 6, 9)]
    handles = [fe.submit(ServeRequest("WYTSARQQ", seed=1))]  # head, no fam
    for m in muts:
        handles.append(fe.submit(ServeRequest(m, seed=1, parent_id="famA")))
    # duplicate (seq, seed): the follower joins the leader's flight
    handles.append(fe.submit(ServeRequest("WYTSARQQ", seed=1)))
    clock.advance(0.051)
    fe.pump()
    results = [h.result(0) for h in handles]
    assert all(r.ok for r in results), [r.status for r in results]
    assert eng.counters.get("sched.affinity_batches") >= 1
    assert eng.counters.get("sched.inflight_dedup") >= 1
    assert eng.counters.get("sched.family_members") >= 3
    ids = [r.trace_id for r in results]
    summary = trace_completeness(eng.tracer.events(), ids)
    assert summary["fraction"] == 1.0, summary
    # recorder-side closure: a submit for every resolve, by trace id
    submits = {e["trace"] for e in rec.events() if e["kind"] == "submit"}
    resolves = [e["trace"] for e in rec.events() if e["kind"] == "resolve"]
    assert len(resolves) == len(handles)
    assert set(resolves) <= submits


# --------------------------------------------------- real-engine cost ledger


def test_served_results_carry_cost_ledger():
    from alphafold2_tpu.serve import ServeEngine

    eng = ServeEngine(_cfg(buckets=(16,), feature_cache_size=16))
    try:
        results = eng.predict_many(
            [ServeRequest("ACDEFGHIKLMN", seed=s) for s in range(3)]
        )
        for r in results:
            assert r.ok and r.cost is not None
            for key in ("queue_wait_s", "device_share_s",
                        "compile_share_s", "flops_share", "pad_fraction"):
                assert key in r.cost and r.cost[key] >= 0
            assert 0.0 <= r.cost["pad_fraction"] < 1.0
        # one compile amortized over the batch's real members
        assert results[0].cost["compile_share_s"] > 0
        assert results[0].cost["device_share_s"] > 0
    finally:
        eng.close()
