"""scripts/tpu_watch.sh — the stage accounting the watcher relies on.

The watcher's hardcoded stage-order list must track tpu_session.STAGES
(importing tpu_session from the shell loop would pay a jax import per
poll cycle, so the list is duplicated and pinned here instead), and its
remaining-stages helper must behave for fresh/partial/complete session
files.
"""

import json
import os
import re
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WATCH = os.path.join(REPO, "scripts", "tpu_watch.sh")

SESSION_STAGES = [
    "first_light", "bench", "baseline", "pallas", "profile", "bisect",
    "train_real", "capacity", "suite",
]


def _watch_order():
    src = open(WATCH).read()
    m = re.search(r"order = \[(.*?)\]", src, re.S)
    assert m, "stage order list not found in tpu_watch.sh"
    return re.findall(r'"(\w+)"', m.group(1))


def test_watch_order_matches_session_stages():
    # parse tpu_session.py's STAGES dict literally (no import: module-level
    # code configures jax) and compare both against the pinned list
    src = open(os.path.join(REPO, "scripts", "tpu_session.py")).read()
    m = re.search(r"STAGES = \{(.*?)\}", src, re.S)
    assert m, "STAGES dict not found in tpu_session.py"
    session = re.findall(r'"(\w+)":', m.group(1))
    assert session == SESSION_STAGES
    assert _watch_order() == SESSION_STAGES


def _remaining(tmp_path, session: dict | None, requested: str = ""):
    """Run the watcher's embedded accounting python exactly as the shell
    does (extracted heredoc body)."""
    src = open(WATCH).read()
    m = re.search(r"<<'PY'.*?\n(.*?)\nPY\n", src, re.S)
    assert m, "accounting heredoc not found"
    out_path = tmp_path / "TPU_SESSION.json"
    if session is not None:
        out_path.write_text(json.dumps(session))
    r = subprocess.run(
        [sys.executable, "-", str(out_path), requested],
        input=m.group(1), capture_output=True, text=True,
    )
    assert r.returncode == 0, r.stderr
    return r.stdout.strip().split()


def test_remaining_all_when_no_file(tmp_path):
    assert _remaining(tmp_path, None) == SESSION_STAGES


def test_remaining_skips_green_stages(tmp_path):
    session = {"stages": {s: {"ok": True} for s in SESSION_STAGES[:4]}}
    assert _remaining(tmp_path, session) == SESSION_STAGES[4:]


def test_remaining_empty_when_all_green(tmp_path):
    session = {"stages": {s: {"ok": True} for s in SESSION_STAGES}}
    assert _remaining(tmp_path, session) == []


def test_bench_rides_with_baseline(tmp_path):
    # baseline consumes its own session's bench result: owed baseline
    # must pull bench back in even when bench is already green
    session = {"stages": {s: {"ok": True} for s in SESSION_STAGES
                          if s != "baseline"}}
    assert _remaining(tmp_path, session) == ["bench", "baseline"]


def test_requested_restricts(tmp_path):
    session = {"stages": {"bench": {"ok": True}}}
    assert _remaining(tmp_path, session, "bench pallas") == ["pallas"]
    assert _remaining(tmp_path, session, "bench") == []
