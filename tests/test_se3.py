"""SE(3)-equivariance oracle tests — numeric verification the reference's
external dependency never had in-repo: rotating/translating the input point
cloud must leave scalar outputs invariant and rotate vector outputs."""

import jax
import jax.numpy as jnp
import numpy as np

from alphafold2_tpu.models.se3 import SE3Refiner, SE3TemplateEmbedder, SE3Transformer


def _rotation(key):
    m = jax.random.normal(key, (3, 3))
    q, r = jnp.linalg.qr(m)
    q = q * jnp.sign(jnp.diagonal(r))
    det = jnp.linalg.det(q)
    return q.at[:, 0].multiply(jnp.sign(det))


def test_scalar_invariance_vector_equivariance():
    key = jax.random.key(0)
    b, n, d, dv = 1, 10, 16, 4
    s = jax.random.normal(jax.random.fold_in(key, 1), (b, n, d))
    v = jax.random.normal(jax.random.fold_in(key, 2), (b, n, dv, 3))
    coords = jax.random.normal(jax.random.fold_in(key, 3), (b, n, 3)) * 4
    model = SE3Transformer(dim=d, depth=2, vec_dim=dv)
    params = model.init(jax.random.key(4), s, v, coords)

    R = _rotation(jax.random.key(5))
    t = jnp.array([1.0, -2.0, 3.0])

    s1, v1 = model.apply(params, s, v, coords)
    s2, v2 = model.apply(
        params, s, jnp.einsum("ij,bncj->bnci", R, v),
        jnp.einsum("ij,bnj->bni", R, coords) + t,
    )
    assert np.allclose(s1, s2, atol=2e-4), np.abs(np.asarray(s1 - s2)).max()
    v1_rot = jnp.einsum("ij,bncj->bnci", R, v1)
    assert np.allclose(v1_rot, v2, atol=2e-4), np.abs(np.asarray(v1_rot - v2)).max()


def test_refiner_equivariance():
    key = jax.random.key(1)
    b, n = 1, 12
    tokens = jax.random.randint(jax.random.fold_in(key, 1), (b, n), 0, 14)
    coords = jax.random.normal(jax.random.fold_in(key, 2), (b, n, 3)) * 5
    mask = jnp.ones((b, n), dtype=bool).at[0, -2:].set(False)
    model = SE3Refiner(dim=32, depth=2, num_tokens=14)
    params = model.init(jax.random.key(3), tokens, coords, mask=mask)

    R = _rotation(jax.random.key(4))
    t = jnp.array([[0.5, 1.5, -0.5]])

    out1 = model.apply(params, tokens, coords, mask=mask)
    out2 = model.apply(
        params, tokens, jnp.einsum("ij,bnj->bni", R, coords) + t, mask=mask
    )
    expected = jnp.einsum("ij,bnj->bni", R, out1) + t
    assert np.allclose(expected, out2, atol=2e-4), np.abs(
        np.asarray(expected - out2)
    ).max()


def test_template_embedder_invariance():
    key = jax.random.key(2)
    b, n, d = 1, 8, 16
    s = jax.random.normal(jax.random.fold_in(key, 1), (b, n, d))
    side = jax.random.normal(jax.random.fold_in(key, 2), (b, n, 3))
    coords = jax.random.normal(jax.random.fold_in(key, 3), (b, n, 3)) * 4
    model = SE3TemplateEmbedder(dim=d, depth=2)
    params = model.init(jax.random.key(4), s, side, coords)

    R = _rotation(jax.random.key(5))
    out1 = model.apply(params, s, side, coords)
    out2 = model.apply(
        params, s, jnp.einsum("ij,bnj->bni", R, side),
        jnp.einsum("ij,bnj->bni", R, coords),
    )
    assert np.allclose(out1, out2, atol=2e-4), np.abs(np.asarray(out1 - out2)).max()
