"""bench.py liveness acceptance (ISSUE 2): with a simulated hung backend,
the bench exits with a structured failure record marked ``liveness: dead``
well inside the 60 s bound, instead of burning its whole deadline the way
round 5 did (BENCH_r05.json: 1500 s hung in backend_init, no signal).

The hang is simulated the same way tests/test_tpu_session_liveness.py does
it — no real backend is harmed: AF2TPU_BENCH_SIMULATE_HANG sleeps inside
the backend_init stage and AF2TPU_LIVENESS_PROBE_CODE makes the subprocess
probe hang like a dead tunnel. Deadlines are scaled down so the test runs
in seconds; the production defaults (30 s stage deadline + 25 s probe
timeout) keep the same path under 60 s.
"""

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_bench_emits_liveness_dead_record_fast():
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        AF2TPU_PLATFORM="cpu",
        # serve mode: no preflight probes in front of the measured path
        AF2TPU_BENCH_MODE="serve",
        AF2TPU_SERVE_BUCKETS="8",
        AF2TPU_SERVE_REQUESTS="2",
        # the simulated hang: backend_init sleeps far past every deadline
        AF2TPU_BENCH_SIMULATE_HANG="backend_init:300",
        AF2TPU_BENCH_INIT_DEADLINE="2",
        AF2TPU_LIVENESS_TIMEOUT="3",
        AF2TPU_LIVENESS_PROBE_CODE="import time; time.sleep(120)",
    )
    t0 = time.monotonic()
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        capture_output=True, text=True, timeout=55, env=env,
    )
    elapsed = time.monotonic() - t0
    # the acceptance bound, with margin: deadline 2s + probe 3s + overhead
    assert elapsed < 55, elapsed

    lines = [ln for ln in r.stdout.splitlines() if ln.strip()]
    assert len(lines) == 1, (r.stdout, r.stderr[-1000:])
    record = json.loads(lines[0])
    assert record["liveness"] == "dead"
    assert record["stage"] == "serve:backend_init"
    assert record["value"] == 0.0
    assert record["vs_baseline_valid"] is False
    assert "liveness dead" in record["error"]
    assert "probe hung" in record["probe"]


def test_bench_compile_phase_dead_tunnel_fails_fast():
    """ROADMAP satellite: the probe-and-bail must cover phases PAST
    backend_init — a tunnel that dies mid-compile used to burn the whole
    remaining deadline. Simulated hang inside serve:trace_compile +
    hanging probe => structured liveness-dead record in seconds."""
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        AF2TPU_PLATFORM="cpu",
        AF2TPU_BENCH_MODE="serve",
        AF2TPU_SERVE_BUCKETS="8",
        AF2TPU_SERVE_REQUESTS="2",
        AF2TPU_SERVE_DIM="32",
        AF2TPU_SERVE_DEPTH="1",
        AF2TPU_SERVE_HEADS="2",
        AF2TPU_SERVE_DIM_HEAD="16",
        AF2TPU_SERVE_MSA_DEPTH="2",
        AF2TPU_SERVE_MDS_ITERS="8",
        # hang INSIDE the compile phase, past a healthy backend_init
        AF2TPU_BENCH_SIMULATE_HANG="trace_compile:300",
        AF2TPU_BENCH_STAGE_DEADLINE="2",
        AF2TPU_LIVENESS_TIMEOUT="3",
        AF2TPU_LIVENESS_PROBE_CODE="import time; time.sleep(120)",
    )
    t0 = time.monotonic()
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        capture_output=True, text=True, timeout=110, env=env,
    )
    elapsed = time.monotonic() - t0
    assert elapsed < 110, elapsed

    lines = [ln for ln in r.stdout.splitlines() if ln.strip()]
    assert len(lines) == 1, (r.stdout, r.stderr[-1000:])
    record = json.loads(lines[0])
    assert record["liveness"] == "dead"
    assert record["stage"] == "serve:trace_compile"
    assert record["value"] == 0.0
    assert "probe hung" in record["probe"]


def test_default_deadlines_fit_the_60s_bound():
    """The production path is stage deadline + probe timeout (+ poll/emit
    overhead); the defaults must leave margin under the 60 s acceptance
    bound so a real dead tunnel also fails fast — in EVERY probed phase,
    not just backend_init."""
    sys.path.insert(0, REPO)
    import importlib

    import bench

    importlib.reload(bench)
    probe_timeout = float(os.environ.get("AF2TPU_LIVENESS_TIMEOUT", 25))
    assert bench.INIT_DEADLINE + probe_timeout <= 58
    assert bench.STAGE_DEADLINE + probe_timeout <= 58


def test_live_backend_is_not_killed(monkeypatch):
    """A healthy-but-slow backend_init (probe passes) must survive the
    stage deadline: the watchdog extends instead of firing."""
    from alphafold2_tpu.observe import LivenessWatchdog

    stage = {"name": "serve:backend_init"}
    fired = []
    wd = LivenessWatchdog(
        stage_fn=lambda: stage["name"],
        deadlines={"backend_init": 0.1},
        on_dead=fired.append,
        probe=lambda: (True, "probe ok"),
        poll_s=0.02,
    ).start()
    time.sleep(0.5)  # several deadlines worth of "slow init"
    stage["name"] = "serve:timed_run"  # init eventually completes
    time.sleep(0.1)
    wd.stop()
    assert fired == []
