"""Sequence-parallel attention tests on the 8-virtual-device CPU mesh:
ring and Ulysses implementations must equal the dense oracle exactly
(they are exact algorithms, not approximations), with masking, under jit,
and through gradients."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from alphafold2_tpu.parallel.seq_parallel import (
    sequence_parallel_attention,
)
from alphafold2_tpu.parallel.sharding import make_mesh

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 (virtual) devices"
)


def _qkv(key, b=2, h=4, n=32, d=8):
    ks = jax.random.split(key, 3)
    return tuple(jax.random.normal(kk, (b, h, n, d)) for kk in ks)


def _dense_oracle(q, k, v, mask=None):
    scale = q.shape[-1] ** -0.5
    dots = jnp.einsum("bhid,bhjd->bhij", q, k) * scale
    if mask is not None:
        dots = jnp.where(mask[:, None, None, :], dots, -1e9)
    return jnp.einsum(
        "bhij,bhjd->bhid", jax.nn.softmax(dots, axis=-1), v
    )


@pytest.mark.parametrize("impl", ["ring", "ulysses"])
def test_matches_dense_oracle(impl):
    q, k, v = _qkv(jax.random.key(0))
    mesh = make_mesh(2, 4)
    out = sequence_parallel_attention(q, k, v, mesh=mesh, impl=impl)
    ref = _dense_oracle(q, k, v)
    assert np.allclose(out, ref, atol=1e-5), np.abs(np.asarray(out - ref)).max()


@pytest.mark.parametrize("impl", ["ring", "ulysses"])
def test_masked_matches_dense_oracle(impl):
    q, k, v = _qkv(jax.random.key(1))
    mask = jnp.ones((2, 32), bool).at[:, 27:].set(False)
    mesh = make_mesh(2, 4)
    out = sequence_parallel_attention(q, k, v, mask=mask, mesh=mesh, impl=impl)
    ref = _dense_oracle(q, k, v, mask=mask)
    # only unmasked queries are meaningful
    assert np.allclose(out[:, :, :27], ref[:, :, :27], atol=1e-5)


def test_ring_under_jit_and_grads():
    q, k, v = _qkv(jax.random.key(2), h=2, n=16)
    mesh = make_mesh(1, 8)

    def loss_sp(q, k, v):
        return jnp.sum(
            sequence_parallel_attention(q, k, v, mesh=mesh, impl="ring") ** 2
        )

    def loss_dense(q, k, v):
        return jnp.sum(_dense_oracle(q, k, v) ** 2)

    g_sp = jax.jit(jax.grad(loss_sp))(q, k, v)
    g_dense = jax.grad(loss_dense)(q, k, v)
    assert np.allclose(g_sp, g_dense, atol=1e-4), (
        np.abs(np.asarray(g_sp - g_dense)).max()
    )


@pytest.mark.parametrize("impl", ["ring", "ulysses"])
def test_cross_attention_different_lengths_no_mask(impl):
    # cross-attention: Nq != Nk, mask=None — the default key bias must be
    # built with the KEY length (regression: it used the query length)
    kq, kk, kv = jax.random.split(jax.random.key(5), 3)
    q = jax.random.normal(kq, (2, 4, 32, 8))
    k = jax.random.normal(kk, (2, 4, 64, 8))
    v = jax.random.normal(kv, (2, 4, 64, 8))
    mesh = make_mesh(2, 4)
    out = sequence_parallel_attention(q, k, v, mesh=mesh, impl=impl)
    ref = _dense_oracle(q, k, v)
    assert np.allclose(out, ref, atol=1e-5)


def test_unknown_impl_rejected():
    q, k, v = _qkv(jax.random.key(6))
    with pytest.raises(ValueError, match="impl"):
        sequence_parallel_attention(q, k, v, mesh=make_mesh(1, 8), impl="Ring")


def test_tied_row_attention_sharded_matches_dense():
    # MSA rows sharded over sp: psum of per-shard logits must equal the
    # dense tied contraction exactly (SURVEY.md S7 "tied-rows becomes a
    # collective")
    from alphafold2_tpu.parallel.seq_parallel import tied_row_attention

    ks = jax.random.split(jax.random.key(7), 3)
    q, k, v = (jax.random.normal(kk, (2, 8, 2, 16, 8)) for kk in ks)
    mesh = make_mesh(2, 4)  # 8 rows / 4-way sharding
    out = tied_row_attention(q, k, v, mesh=mesh)
    ref = tied_row_attention(q, k, v, mesh=None)
    assert np.allclose(out, ref, atol=1e-5), np.abs(np.asarray(out - ref)).max()

    # gradients flow through the psum identically to the dense contraction
    g = jax.grad(lambda q: jnp.sum(tied_row_attention(q, k, v, mesh=mesh) ** 2))(q)
    gd = jax.grad(lambda q: jnp.sum(tied_row_attention(q, k, v, mesh=None) ** 2))(q)
    assert np.allclose(g, gd, atol=1e-4)


def test_dense_fallback_without_mesh():
    q, k, v = _qkv(jax.random.key(3))
    out = sequence_parallel_attention(q, k, v, mesh=None)
    assert np.allclose(out, _dense_oracle(q, k, v), atol=1e-5)


def test_ulysses_rejects_indivisible_heads():
    q, k, v = _qkv(jax.random.key(4), h=3)
    mesh = make_mesh(1, 8)
    with pytest.raises(ValueError, match="heads"):
        sequence_parallel_attention(q, k, v, mesh=mesh, impl="ulysses")
