"""REAL multi-process pod test: two OS processes, each owning 4 virtual CPU
devices, bootstrap one 8-device pod via ``jax.distributed`` and run a
sharded training step on a globally-assembled batch. This exercises the
actual DCN-path code (process init, cross-process mesh,
``make_array_from_process_local_data``, collective gradient psum) that the
single-process suite can only emulate — and that the reference has no
analogue of at all (SURVEY.md S2.3)."""

import os
import socket
import subprocess
import sys
from concurrent.futures import ThreadPoolExecutor
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parents[1]


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.slow
def test_two_process_pod_step():
    port = _free_port()
    env = {
        k: v for k, v in os.environ.items()
        if k not in ("XLA_FLAGS", "JAX_PLATFORMS")
    }
    env["PALLAS_AXON_POOL_IPS"] = ""
    procs = [
        subprocess.Popen(
            [sys.executable, str(REPO / "tests" / "_multihost_child.py"),
             str(pid), str(port)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env, cwd=str(REPO),
        )
        for pid in (0, 1)
    ]
    try:
        # drain both children concurrently: sequential communicate() could
        # deadlock if the not-yet-read child fills its pipe buffer while
        # the other blocks on a collective
        with ThreadPoolExecutor(2) as pool:
            results = list(
                pool.map(lambda p: p.communicate(timeout=540), procs)
            )
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for p, (out, err) in zip(procs, results):
        if "Multiprocess computations aren't implemented on the CPU backend" in err:
            pytest.skip(
                "environment gate: this jax build's CPU backend has no "
                "cross-process collectives (XlaRuntimeError: Multiprocess "
                "computations aren't implemented on the CPU backend)"
            )
        assert p.returncode == 0, (out[-500:], err[-2000:])

    losses = {}
    for out, _err in results:
        for line in out.splitlines():
            if line.startswith("RANK"):
                _, rank, _, loss, _, gnorm = line.split()
                losses[int(rank)] = (float(loss), float(gnorm))
    assert set(losses) == {0, 1}, results
    # both ranks computed the SAME global step: loss and grad norm agree
    assert losses[0] == losses[1], losses
