"""Unit tests for the observability subsystem (alphafold2_tpu/observe):
tracer span emission in valid Chrome trace-event format, streaming
histogram percentiles, thread-safe counters, MetricsLogger JSONL output
and jax-free construction, memory sampler no-op behavior, Profiler
step-window logic, and the liveness watchdog's dead/alive verdicts."""

import json
import threading
import time

import numpy as np
import pytest

from alphafold2_tpu.observe import (
    EventCounters,
    Histogram,
    LivenessWatchdog,
    MemorySampler,
    MetricsLogger,
    Profiler,
    Tracer,
    probe_backend,
)
from alphafold2_tpu.observe.tracing import load_trace_events


# ------------------------------------------------------------------ tracer


def _assert_valid_chrome_events(events):
    """Every event carries the Chrome trace-event required keys with the
    right types (what Perfetto/chrome://tracing expects)."""
    assert events, "no events emitted"
    for e in events:
        assert isinstance(e["name"], str) and e["name"]
        assert e["ph"] in ("X", "i", "C")
        assert isinstance(e["ts"], (int, float)) and e["ts"] >= 0
        assert isinstance(e["pid"], int)
        if e["ph"] == "X":
            assert isinstance(e["dur"], (int, float)) and e["dur"] >= 0
            assert isinstance(e["tid"], int)


def test_tracer_emits_nested_spans_to_file(tmp_path):
    path = str(tmp_path / "trace.json")
    tracer = Tracer(path)
    with tracer.span("outer", kind="test"):
        with tracer.span("inner"):
            time.sleep(0.01)
    tracer.instant("marker", note="hi")
    tracer.counter("mem", bytes=123)
    tracer.close()

    events = load_trace_events(path)
    _assert_valid_chrome_events(events)
    by_name = {e["name"]: e for e in events}
    assert set(by_name) == {"outer", "inner", "marker", "mem"}
    outer, inner = by_name["outer"], by_name["inner"]
    # nesting: inner starts after outer and ends before it (ts+dur)
    assert inner["ts"] >= outer["ts"]
    assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"] + 1
    assert inner["dur"] >= 10_000 * 0.5  # slept 10ms, dur is in us
    assert outer["args"] == {"kind": "test"}


def test_tracer_file_is_chrome_loadable_streaming_array(tmp_path):
    """The on-disk form: opens with '[', one JSON object per line with a
    trailing comma — the trace-event spec's streaming JSON array (closing
    ']' optional), which is also line-parseable as JSONL."""
    path = str(tmp_path / "trace.json")
    tracer = Tracer(path)
    with tracer.span("a"):
        pass
    tracer.close()
    lines = open(path).read().splitlines()
    assert lines[0] == "["
    for line in lines[1:]:
        json.loads(line.rstrip(","))  # each line parses standalone


def test_tracer_span_records_exception_and_reraises(tmp_path):
    tracer = Tracer(str(tmp_path / "t.json"))
    with pytest.raises(ValueError):
        with tracer.span("dies"):
            raise ValueError("boom")
    (event,) = tracer.events()
    assert event["args"]["error"] == "ValueError"


def test_tracer_disabled_is_noop():
    tracer = Tracer(enabled=False)
    with tracer.span("x") as sp:
        sp.set(a=1)  # null span accepts set()
    tracer.instant("y")
    assert tracer.events() == []
    assert tracer.span_totals() == {}


def test_tracer_span_totals():
    tracer = Tracer(enabled=None, path=None)
    tracer.enabled = True  # in-memory only
    for _ in range(3):
        with tracer.span("work"):
            pass
    totals = tracer.span_totals()
    assert totals["work"]["count"] == 3
    assert totals["work"]["total_s"] >= 0.0


def test_tracer_set_attaches_args():
    tracer = Tracer(enabled=True)
    with tracer.span("s") as sp:
        sp.set(verdict="hit")
    (e,) = tracer.events()
    assert e["args"]["verdict"] == "hit"


def test_tracer_threads_get_distinct_tids():
    tracer = Tracer(enabled=True)
    barrier = threading.Barrier(4)  # all threads alive at once: the OS
    # cannot recycle a finished thread's id into another span's tid

    def work():
        with tracer.span("t"):
            barrier.wait(timeout=10)

    threads = [threading.Thread(target=work) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    tids = {e["tid"] for e in tracer.events()}
    assert len(tids) == 4


# --------------------------------------------------------------- histogram


def test_histogram_percentiles_on_known_stream():
    h = Histogram()
    for v in range(1, 1001):  # 1..1000
        h.observe(float(v))
    assert h.count == 1000
    # log-bucketed estimate: within the bucket's relative error
    assert abs(h.percentile(50) - 500) / 500 < 0.08
    assert abs(h.percentile(95) - 950) / 950 < 0.08
    assert abs(h.percentile(99) - 990) / 990 < 0.08
    snap = h.snapshot()
    assert snap["count"] == 1000
    assert snap["min"] == 1.0 and snap["max"] == 1000.0
    assert abs(snap["mean"] - 500.5) < 1e-6
    assert snap["p50"] <= snap["p95"] <= snap["p99"] <= snap["max"]


def test_histogram_zeros_and_unit_scale():
    h = Histogram()
    for _ in range(10):
        h.observe(0.0)
    h.observe(0.5)
    assert h.percentile(50) == 0.0
    snap = h.snapshot(unit_scale=1e3)
    assert snap["max"] == 500.0  # 0.5 s -> ms
    assert snap["p50"] == 0.0


def test_histogram_empty_and_invalid():
    h = Histogram()
    assert h.snapshot() == {"count": 0}
    assert h.percentile(99) == 0.0
    with pytest.raises(ValueError):
        h.observe(-1.0)
    with pytest.raises(ValueError):
        h.observe(float("nan"))
    with pytest.raises(ValueError):
        Histogram(growth=1.0)


def test_histogram_thread_safety():
    h = Histogram()

    def work():
        for v in range(1, 501):
            h.observe(float(v))

    threads = [threading.Thread(target=work) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert h.count == 2000
    assert h.snapshot()["max"] == 500.0


# ---------------------------------------------------------------- counters


def test_event_counters_thread_safe_bumps():
    """Concurrent bumps from many threads must not lose updates (the
    watchdog/heartbeat threads bump beside the dispatch path)."""
    c = EventCounters()

    def work():
        for _ in range(1000):
            c.bump("n")

    threads = [threading.Thread(target=work) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.get("n") == 8000
    assert c.snapshot() == {"n": 8000}


def test_event_counters_basics():
    c = EventCounters()
    assert c.get("missing") == 0
    assert c.bump("a") == 1
    assert c.bump("a", 4) == 5
    assert c.snapshot() == {"a": 5}


# ----------------------------------------------------------- MetricsLogger


def test_metrics_logger_jsonl_output(tmp_path, capsys):
    logger = MetricsLogger(str(tmp_path), enabled=True)
    logger.log(0, {"loss": 1.5, "note": "warm"})
    logger.log(1, {"loss": 0.5})
    lines = (tmp_path / "metrics.jsonl").read_text().splitlines()
    assert len(lines) == 2
    rec0, rec1 = (json.loads(ln) for ln in lines)
    assert rec0 == {"step": 0, "time": rec0["time"], "loss": 1.5,
                    "note": "warm"}
    assert rec1["step"] == 1 and rec1["loss"] == 0.5
    assert rec1["time"] >= rec0["time"]
    out = capsys.readouterr().out
    assert "[step 0]" in out and "loss=1.5" in out


def test_metrics_logger_disabled_writes_nothing(tmp_path, capsys):
    logger = MetricsLogger(str(tmp_path / "sub"), enabled=False)
    logger.log(0, {"loss": 1.0})
    assert not (tmp_path / "sub").exists()
    assert capsys.readouterr().out == ""


def test_metrics_logger_echo_off_keeps_stdout_clean(tmp_path, capsys):
    logger = MetricsLogger(str(tmp_path), enabled=True, echo=False)
    logger.log(0, {"v": 1})
    assert capsys.readouterr().out == ""
    assert (tmp_path / "metrics.jsonl").exists()


def test_metrics_logger_constructs_without_jax(tmp_path, monkeypatch):
    """enabled=None must fall back gracefully when jax import/process_index
    fails (tools running before jax.distributed init, or without jax)."""
    import builtins

    real_import = builtins.__import__

    def no_jax(name, *a, **kw):
        if name == "jax":
            raise ImportError("no jax in this interpreter")
        return real_import(name, *a, **kw)

    monkeypatch.setattr(builtins, "__import__", no_jax)
    logger = MetricsLogger(str(tmp_path), echo=False)
    assert logger.enabled is True
    logger.log(3, {"x": 1.0})
    assert json.loads(
        (tmp_path / "metrics.jsonl").read_text()
    )["x"] == 1.0


# ----------------------------------------------------------- MemorySampler


def test_memory_sampler_graceful_without_stats():
    class Dev:
        id = 0

        def memory_stats(self):
            return None  # CPU-backend behavior

    s = MemorySampler(devices=[Dev()])
    assert s.sample() == []
    assert s.peak_bytes() is None
    s.log_to(MetricsLogger(enabled=False))  # must not raise


def test_memory_sampler_reads_stats_and_logs(tmp_path):
    class Dev:
        def __init__(self, i, peak):
            self.id = i
            self._peak = peak

        def memory_stats(self):
            return {"bytes_in_use": 10, "peak_bytes_in_use": self._peak,
                    "bytes_limit": 100}

    s = MemorySampler(devices=[Dev(0, 40), Dev(1, 70)])
    recs = s.sample()
    assert len(recs) == 2
    assert s.peak_bytes() == 70
    logger = MetricsLogger(str(tmp_path), enabled=True, echo=False)
    s.log_to(logger)
    rec = json.loads((tmp_path / "metrics.jsonl").read_text())
    assert rec["hbm_peak_bytes"] == 70 and rec["hbm_devices"] == 2

    tracer = Tracer(enabled=True)
    s.counter_to(tracer)
    counters = [e for e in tracer.events() if e["ph"] == "C"]
    assert len(counters) == 2
    assert counters[0]["args"]["peak_bytes_in_use"] == 40


def test_memory_sampler_on_real_backend():
    """Whatever this host's backend exposes, sample() must not raise and
    peak_bytes() must be a positive int or None."""
    peak = MemorySampler().peak_bytes()
    assert peak is None or peak > 0


# ---------------------------------------------------------------- Profiler


class _FakeProfiler:
    def __init__(self):
        self.calls = []

    def install(self, monkeypatch):
        import jax

        monkeypatch.setattr(
            jax.profiler, "start_trace",
            lambda d: self.calls.append(("start", d)),
        )
        monkeypatch.setattr(
            jax.profiler, "stop_trace", lambda: self.calls.append(("stop",))
        )


def test_profiler_window_boundaries(monkeypatch, tmp_path):
    fake = _FakeProfiler()
    fake.install(monkeypatch)
    p = Profiler(str(tmp_path), steps=(2, 4))
    for step in range(6):
        p.maybe_start(step)
        p.maybe_stop(step)
    # starts exactly at step 2; stop fires at the first step >= 4 — but
    # maybe_stop(2) and (3) run while active and must NOT stop early
    assert fake.calls == [("start", str(tmp_path)), ("stop",)]


def test_profiler_no_dir_never_starts(monkeypatch):
    fake = _FakeProfiler()
    fake.install(monkeypatch)
    p = Profiler(None, steps=(0, 1))
    for step in range(3):
        p.maybe_start(step)
        p.maybe_stop(step)
    assert fake.calls == []


def test_profiler_reentry_safety(monkeypatch, tmp_path):
    """Calling maybe_start repeatedly at the start step must start ONE
    trace; maybe_stop past the window with no active trace is a no-op."""
    fake = _FakeProfiler()
    fake.install(monkeypatch)
    p = Profiler(str(tmp_path), steps=(1, 2))
    p.maybe_start(1)
    p.maybe_start(1)  # re-entry: already active
    assert fake.calls.count(("start", str(tmp_path))) == 1
    p.maybe_stop(5)
    p.maybe_stop(6)  # already stopped
    assert fake.calls == [("start", str(tmp_path)), ("stop",)]
    # a fresh window instance would start again at its own start step
    p.maybe_start(1)
    assert fake.calls[-1] == ("start", str(tmp_path))


# ---------------------------------------------------------------- watchdog


def _run_watchdog(stage, deadlines, probe, timeout=5.0):
    fired = []
    done = threading.Event()

    def on_dead(rec):
        fired.append(rec)
        done.set()

    wd = LivenessWatchdog(
        stage_fn=lambda: stage["name"], deadlines=deadlines,
        on_dead=on_dead, probe=probe, poll_s=0.05,
    ).start()
    done.wait(timeout)
    wd.stop()
    return fired


def test_watchdog_fires_dead_on_hung_stage():
    stage = {"name": "backend_init"}
    t0 = time.monotonic()
    fired = _run_watchdog(
        stage, {"backend_init": 0.2},
        probe=lambda: (False, "probe hung >1s (dead tunnel)"),
    )
    elapsed = time.monotonic() - t0
    assert len(fired) == 1
    rec = fired[0]
    assert rec["liveness"] == "dead"
    assert rec["stage"] == "backend_init"
    assert rec["probe"] == "probe hung >1s (dead tunnel)"
    assert rec["waited_s"] >= 0.2
    assert elapsed < 5.0  # seconds, not a bench deadline


def test_watchdog_suffix_matches_prefixed_stages():
    stage = {"name": "serve:backend_init"}
    fired = _run_watchdog(
        stage, {"backend_init": 0.1}, probe=lambda: (False, "dead")
    )
    assert fired and fired[0]["stage"] == "serve:backend_init"


def test_watchdog_alive_probe_extends_instead_of_firing():
    stage = {"name": "backend_init"}
    probes = []

    def probe():
        probes.append(time.monotonic())
        return True, "probe ok"

    fired = _run_watchdog(stage, {"backend_init": 0.15}, probe, timeout=0.7)
    assert fired == []  # alive backend: never declared dead
    assert len(probes) >= 2  # but it kept re-checking each deadline


def test_watchdog_stage_progress_resets_clock():
    stage = {"name": "backend_init"}
    probes = []

    def probe():
        probes.append(1)
        return False, "dead"

    done = threading.Event()
    wd = LivenessWatchdog(
        stage_fn=lambda: stage["name"], deadlines={"backend_init": 0.3},
        on_dead=lambda rec: done.set(), probe=probe, poll_s=0.05,
    ).start()
    # keep making progress: the deadline never accumulates 0.3s in one stage
    for i in range(4):
        time.sleep(0.15)
        stage["name"] = f"phase_{i}:backend_init"
    assert not done.is_set() and probes == []
    wd.stop()


def test_watchdog_unlisted_stage_is_unbounded():
    stage = {"name": "timed_run"}
    fired = _run_watchdog(
        stage, {"backend_init": 0.05}, probe=lambda: (False, "dead"),
        timeout=0.4,
    )
    assert fired == []


def test_probe_backend_simulated_hang_times_out(monkeypatch):
    monkeypatch.setenv(
        "AF2TPU_LIVENESS_PROBE_CODE", "import time; time.sleep(60)"
    )
    t0 = time.monotonic()
    alive, why = probe_backend(timeout=1)
    assert alive is False
    assert "hung" in why
    assert time.monotonic() - t0 < 10


def test_probe_backend_trivial_code_passes():
    alive, why = probe_backend(timeout=60, code="pass")
    assert alive, why


# ------------------------------------------------------- train-loop wiring


def test_train_loop_emits_step_spans(tmp_path):
    """train() with train.trace_events set writes a Chrome trace with one
    train.step span per executed step (plus batch-fetch spans)."""
    from alphafold2_tpu.config import (
        Config, DataConfig, ModelConfig, TrainConfig,
    )
    from alphafold2_tpu.train.loop import train

    path = str(tmp_path / "train_trace.json")
    cfg = Config(
        model=ModelConfig(dim=32, depth=1, heads=2, dim_head=16,
                          max_seq_len=64, bfloat16=False),
        data=DataConfig(crop_len=16, msa_depth=2, msa_len=16, batch_size=1,
                        min_len_filter=8),
        train=TrainConfig(num_steps=2, gradient_accumulate_every=1,
                          warmup_steps=1, log_every=10, trace_events=path),
    )
    train(cfg)
    events = load_trace_events(path)
    _assert_valid_chrome_events(events)
    steps = [e for e in events if e["name"] == "train.step"]
    assert len(steps) == 2
    assert [e["args"]["step"] for e in steps] == [0, 1]
    assert any(e["name"] == "train.next_batch" for e in events)


# ------------------------------------------------------------- shim imports


def test_train_observe_shim_reexports():
    from alphafold2_tpu.train import observe as shim

    assert shim.MetricsLogger is MetricsLogger
    assert shim.EventCounters is EventCounters
    assert shim.Profiler is Profiler
    assert shim.Tracer is Tracer
    assert shim.Histogram is Histogram
