"""MDS tests: shape parity with the reference suite plus a real reconstruction
oracle (recover coordinates from their own distance matrix) and jit/grad
compatibility — the reference's MDS cannot run under a compiler at all."""

import jax
import jax.numpy as jnp
import numpy as np

from alphafold2_tpu.utils import (
    Kabsch,
    MDScaling,
    RMSD,
    cdist,
    center_distogram,
    mds,
    mdscaling_backbone,
    scn_backbone_mask,
)


def test_mds_shape_from_distogram():
    # mirror of reference tests/test_utils.py:18-35
    key = jax.random.key(0)
    logits = jax.random.normal(key, (1, 32 * 3, 32 * 3, 37))
    probs = jax.nn.softmax(logits, axis=-1)
    distances, weights = center_distogram(probs)
    masker = np.arange(96) % 3
    coords, history = MDScaling(
        distances,
        weights=weights,
        iters=50,
        fix_mirror=True,
        N_mask=jnp.asarray(masker == 0),
        CA_mask=jnp.asarray(masker == 1),
        C_mask=None,
    )
    assert coords.shape == (1, 3, 96)
    assert history.shape[0] == 50
    assert np.all(np.isfinite(coords))


def test_mds_reconstructs_geometry():
    # ground-truth coords -> exact distance matrix -> MDS -> Kabsch-aligned RMSD
    key = jax.random.key(42)
    true = jax.random.normal(key, (1, 24, 3)) * 4.0
    dist = cdist(true, true)
    coords, _ = mds(dist, iters=500, tol=0.0, key=jax.random.key(7))
    pred = coords[0]  # (3, N)
    target = true[0].T
    a, b = Kabsch(pred, target)
    direct = float(RMSD(a, b)[0])
    # MDS can land on the mirror image; accept either chirality
    am, bm = Kabsch(pred.at[-1].multiply(-1.0), target)
    mirrored = float(RMSD(am, bm)[0])
    assert min(direct, mirrored) < 0.5


def test_mds_jittable_and_differentiable():
    key = jax.random.key(0)
    true = jax.random.normal(key, (2, 12 * 3, 3)) * 3.0
    dist = cdist(true, true)

    @jax.jit
    def realize(d):
        coords, _ = mdscaling_backbone(d, iters=20, key=jax.random.key(1))
        return coords

    coords = realize(dist)
    assert coords.shape == (2, 3, 36)

    def loss(d):
        coords, _ = mdscaling_backbone(d, iters=10, key=jax.random.key(1))
        return jnp.sum(coords**2)

    g = jax.jit(jax.grad(loss))(dist)
    assert g.shape == dist.shape
    assert np.all(np.isfinite(g))


def test_mirror_fix_per_batch_element():
    # two copies of the same structure, one pre-mirrored: after fix both should
    # have the same chirality (matching negative-phi majority)
    key = jax.random.key(5)
    true = jax.random.normal(key, (1, 10 * 3, 3)) * 3.0
    dist = cdist(true, true)
    batch = jnp.concatenate([dist, dist], axis=0)
    coords, _ = mdscaling_backbone(batch, iters=200, key=jax.random.key(3))
    from alphafold2_tpu.utils import calc_phis_backbone

    ratios = np.asarray(calc_phis_backbone(coords))
    # after the per-element fix, every element has >= 0.5 negative-phi ratio
    assert np.all(ratios >= 0.5)


def test_backbone_mask_matches_masked_calc():
    # reshape-based phi calc == boolean-mask phi calc on the (N,CA,C)* layout
    from alphafold2_tpu.utils import calc_phis, calc_phis_backbone

    coords = jax.random.normal(jax.random.key(9), (2, 3, 30))
    seq = jnp.zeros((2, 10), dtype=jnp.int32)
    n_mask, ca_mask = scn_backbone_mask(seq, l_aa=3)
    masked = np.asarray(calc_phis(coords, n_mask, ca_mask))
    reshaped = np.asarray(calc_phis_backbone(coords))
    assert np.allclose(masked, reshaped, atol=1e-6)
