"""Benchmark: distogram-pretraining step throughput on the flagship config.

Primary metric (BASELINE.md): residue-pairs/sec/chip at crop 256. The
reference publishes no numbers (BASELINE.json "published": {}), so
``vs_baseline`` is measured against the first recorded run of this bench
(bench_baseline.json, committed after the first TPU run) — i.e. the
framework competes against its own round-1 number.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import json
import os
import sys
import time

import alphafold2_tpu

alphafold2_tpu.setup_platform()  # AF2TPU_PLATFORM=cpu for host-side smokes

import jax
import jax.numpy as jnp


def _env_int(name: str, default: int) -> int:
    return int(os.environ.get(name, default))


# flagship config; AF2TPU_BENCH_* env overrides allow small smoke runs on
# hosts without an accelerator (the driver runs the defaults on TPU)
_T0 = time.monotonic()

CROP = _env_int("AF2TPU_BENCH_CROP", 256)
MSA_DEPTH = _env_int("AF2TPU_BENCH_MSA_DEPTH", 16)
MSA_LEN = _env_int("AF2TPU_BENCH_MSA_LEN", 256)
DIM = _env_int("AF2TPU_BENCH_DIM", 256)
DEPTH = _env_int("AF2TPU_BENCH_DEPTH", 2)
BATCH = _env_int("AF2TPU_BENCH_BATCH", 1)
WARMUP = _env_int("AF2TPU_BENCH_WARMUP", 3)
ITERS = _env_int("AF2TPU_BENCH_ITERS", 10)
# steps chained in-graph per dispatch (lax.scan): isolates device throughput
# from host/tunnel dispatch latency
INGRAPH = _env_int("AF2TPU_BENCH_INGRAPH", 8)  # scan trip count: compile
# cost is INGRAPH-independent, and 8 halves the per-dispatch tunnel-latency
# share vs 4
# total wall-clock budget (s): the bench must emit its JSON line before the
# driver's own timeout would kill it with nothing on stdout (round 1 lost
# both artifacts to rc=124). Healthy flagship runs finish in well under half
# of this; a hung/flaky backend gets a diagnostic record instead of silence.
# <= 0 disables the watchdog. Default leaves margin under the observed
# >=30 min driver budget while tolerating a slow (~5 min) tunnel compile.
DEADLINE = _env_int("AF2TPU_BENCH_DEADLINE", 1500)
# per-stage liveness deadline (observe.LivenessWatchdog): a dead-at-start
# backend must yield a structured `liveness: dead` failure record in
# seconds, not eat the whole DEADLINE hung in backend_init (BENCH_r05 lost
# its entire 1500s exactly so). When a backend_init phase overstays this,
# a subprocess probe (AF2TPU_LIVENESS_TIMEOUT, default 25s) decides dead
# (fail fast, record marked liveness: dead — total < 60s with defaults)
# vs slow-but-alive (the stage earns another deadline). <= 0 disables.
INIT_DEADLINE = _env_int("AF2TPU_BENCH_INIT_DEADLINE", 30)
# the same probe-and-bail for every LATER stage (ROADMAP: a tunnel that
# dies mid-round used to burn the remaining DEADLINE hung inside a compile
# or dispatch with nothing on stdout): trace_compile / warmup_run /
# clock_probe / timed_run (and their serve:* / first_light:* variants via
# the watchdog's suffix matching) overstaying this trigger the subprocess
# probe — dead backend => structured failure in stage + probe seconds
# (default 30 + 25 < the 60 s acceptance bound); slow-but-alive (a long
# flagship compile — common, compiles are minutes through the tunnel)
# earns the stage another deadline and the round continues, at the cost
# of one cheap probe per deadline interval. <= 0 disables.
STAGE_DEADLINE = _env_int("AF2TPU_BENCH_STAGE_DEADLINE", 30)


# ATTEMPTS/DEADLINE/COLD_EXTRA/DRIVER_BUDGET tune retry/timeout infra, not
# the measured config
_INFRA_KNOBS = {
    "AF2TPU_BENCH_ATTEMPTS", "AF2TPU_BENCH_DEADLINE",
    "AF2TPU_BENCH_COLD_EXTRA", "AF2TPU_BENCH_DRIVER_BUDGET",
    "AF2TPU_BENCH_EPOCH0",  # wall-clock anchor set by __main__ itself
    "AF2TPU_BENCH_FIRST_LIGHT",  # fallback policy, not a config size
    "AF2TPU_BENCH_MODE",  # train vs serve routing, not a config size
    "AF2TPU_BENCH_INIT_DEADLINE",  # liveness watchdog, not a config size
    "AF2TPU_BENCH_STAGE_DEADLINE",  # liveness watchdog, not a config size
    "AF2TPU_BENCH_SIMULATE_HANG",  # liveness-test hook, not a config size
}


def config_overridden() -> bool:
    """True when AF2TPU_BENCH_* env overrides change the measured config —
    such runs must be neither compared against nor recorded as the
    flagship baseline."""
    return any(
        k.startswith("AF2TPU_BENCH_") and k not in _INFRA_KNOBS
        for k in os.environ
    )


def _metric(crop=None, msa_depth=None, msa_len=None, dim=None, depth=None,
            batch=None) -> str:
    """One label for success and failure records — the driver correlates
    records for the same config by this string."""
    return (
        f"residue-pairs/sec/chip crop={crop or CROP} "
        f"msa={msa_depth or MSA_DEPTH}x{msa_len or MSA_LEN} "
        f"dim={dim or DIM} depth={depth or DEPTH} "
        f"batch={batch or BATCH} fwd+bwd+opt"
    )


# which phase of the measurement the process is in — the watchdog's failure
# record reports it, so "backend init never returned" is distinguishable
# from "compile/run exceeded deadline" (VERDICT r3 #1b)
_PHASE = {"name": "startup"}

# a completed smaller-config measurement held as the fallback result: if
# the flagship attempt then hangs or exceeds the deadline, the watchdog
# emits THIS instead of a value-0.0 failure record, so any healthy tunnel
# window yields a nonzero number (VERDICT r3 #1a)
_FIRST_LIGHT = {"record": None}

# one clock validation per process (first_light + flagship share it)
_CLOCK = {"probe": None}


from contextlib import contextmanager

from alphafold2_tpu.observe import (
    LivenessWatchdog,
    MemorySampler,
    MetricsLogger,
    Tracer,
)
from alphafold2_tpu.observe import exposition, flightrec
from alphafold2_tpu.observe.tracing import device_idle_fraction

# the tree's single cost_analysis()/MFU implementation (observe.flops):
# bench, the serve engine, the train loop and bisect_perf all share it
from alphafold2_tpu.observe.flops import (
    PEAK_FLOPS as _PEAK_FLOPS,
    SANITY_FLOPS_CEILING as _SANITY_FLOPS_CEILING,
    estimate_mfu as _estimate_mfu,
    step_flops as _step_flops,
)


def _tracer() -> Tracer:
    """Span tracer for this bench invocation: Chrome trace-event JSONL at
    $AF2TPU_TRACE_EVENTS (Perfetto-loadable), disabled when unset. The
    active flight recorder (if any) rides along as a sink, so its ring
    buffer sees every span the file does."""
    t = Tracer.from_env()
    rec = flightrec.active()
    if rec is not None and t.enabled:
        rec.attach(t)
    return t


def _metrics_logger():
    """Structured JSONL metrics at $AF2TPU_METRICS_DIR/metrics.jsonl
    (compile records, counters, HBM peaks — obs_report.py reads it);
    None when unset. enabled=True: the bench is single-process, and the
    logger must not touch jax.process_index() before backend init."""
    directory = os.environ.get("AF2TPU_METRICS_DIR")
    if not directory:
        return None
    return MetricsLogger(directory, enabled=True, echo=False)


@contextmanager
def _bench_stage(tracer: Tracer, name: str, **args):
    """One bench stage: sets the watchdog-visible phase and opens a span."""
    _PHASE["name"] = name
    _maybe_simulate_hang(name)
    with tracer.span(f"bench.{name}", **args) as sp:
        yield sp


def _maybe_simulate_hang(stage: str) -> None:
    """Test hook: AF2TPU_BENCH_SIMULATE_HANG="<substring>:<seconds>" sleeps
    inside the first stage whose name contains the substring — a stand-in
    for a backend hung in C++ (the liveness watchdog tests drive bench.py
    end to end with it). Inert when unset."""
    spec = os.environ.get("AF2TPU_BENCH_SIMULATE_HANG")
    if not spec:
        return
    name, _, secs = spec.partition(":")
    if name and name in stage:
        time.sleep(float(secs or 3600))


def _clock_probe(m: int | None = None, size: int = 4096, iters: int = 4):
    """Validate that the timing sync actually tracks device completion.

    Round 1 and round 4 both recorded physically impossible throughput
    because the tunneled backend acknowledged block_until_ready (and
    possibly device_get) before the device finished. The >peak-FLOPs guard
    only catches inflation past 100% MFU; a partially-async clock inflating
    3x at a true 10% MFU passes it silently (ADVICE r4). This probe times
    the SAME dispatch count at two in-graph work factors — a scan of M vs
    2M chained matmuls. The dispatch/ack path is identical for both, so a
    device-tracking clock shows ~2x elapsed; an early-acking clock shows
    ~1x. No ground-truth step cost is needed.
    """
    m = m or _env_int("AF2TPU_CLOCK_PROBE_CHAIN", 384)
    x = jnp.ones((size, size), jnp.bfloat16)

    def chain(n):
        def body(c, _):
            return (c @ x) * (1.0 / size), ()

        def f(x0):
            out, _ = jax.lax.scan(body, x0, None, length=n)
            return jnp.sum(out[:1, :1].astype(jnp.float32))

        return jax.jit(f)

    times = []
    for f in (chain(m), chain(2 * m)):
        s = f(x)
        jax.device_get(s)  # compile + warm outside the timed region
        t0 = time.perf_counter()
        for _ in range(iters):
            s = f(x)
        jax.device_get(s)
        times.append(time.perf_counter() - t0)
    # The verdict is physics, not a fixed ratio (a fixed threshold falsely
    # flags an honest clock behind a high-latency relay, where the constant
    # round-trip compresses the ratio): the 2x leg runs iters*m extra
    # matmuls of KNOWN cost. An honest clock's elapsed delta must be at
    # least that work at the chip's peak; a delta implying >peak FLOPs/s
    # means the sync acked before the device finished. Constant round-trip
    # cost cancels in the subtraction.
    extra_flops = iters * m * 2 * size**3
    delta = times[1] - times[0]
    implied = extra_flops / max(delta, 1e-9)
    kind = jax.devices()[0].device_kind
    peak = next(
        (v for k, v in _PEAK_FLOPS.items() if k.lower() in kind.lower()),
        None,
    )
    # 1.25x headroom over peak absorbs timer jitter on the known chip;
    # unknown chips fall back to the global plausibility ceiling
    ceiling = peak * 1.25 if peak else _SANITY_FLOPS_CEILING
    return {
        "t_1x": round(times[0], 4),
        "t_2x": round(times[1], 4),
        "extra_work_tflop": round(extra_flops / 1e12, 1),
        "implied_flops_per_s": float(f"{implied:.3g}"),
        "ceiling_flops_per_s": float(f"{ceiling:.3g}"),
        "ok": bool(delta > 0 and implied <= ceiling),
    }


def main(overrides: dict | None = None, emit: bool = True,
         tracer: Tracer | None = None):
    o = overrides or {}
    owns_tracer = tracer is None
    tracer = tracer if tracer is not None else _tracer()
    crop = o.get("crop", CROP)
    msa_depth = o.get("msa_depth", MSA_DEPTH)
    msa_len = o.get("msa_len", MSA_LEN)
    dim = o.get("dim", DIM)
    depth = o.get("depth", DEPTH)
    batch = o.get("batch", BATCH)
    phase_prefix = "first_light:" if overrides else ""
    from alphafold2_tpu.config import Config, DataConfig, ModelConfig, TrainConfig
    from alphafold2_tpu.data.pipeline import SyntheticDataset
    from alphafold2_tpu.train.loop import (
        build_model,
        device_put_batch,
        make_train_step,
        tiny_init_state,
    )

    cfg = Config(
        model=ModelConfig(
            dim=dim, depth=depth, heads=8, dim_head=64, max_seq_len=crop * 2,
            msa_tie_row_attn=True, bfloat16=True,
        ),
        data=DataConfig(
            crop_len=crop, msa_depth=msa_depth, msa_len=msa_len,
            batch_size=batch,
            min_len_filter=crop,  # full-length crops for a stable FLOP count
        ),
        train=TrainConfig(gradient_accumulate_every=1, warmup_steps=10),
    )

    with _bench_stage(tracer, phase_prefix + "backend_init"):
        data_batch = next(iter(SyntheticDataset(cfg.data, seed=0)))
        model = build_model(cfg)
        # init at tiny slices of the batch: identical params, none of the
        # full-size init compile (train.loop.tiny_init_state)
        state = tiny_init_state(cfg, model, data_batch)
        raw_step = make_train_step(model, mesh=None, jit=False)
        dev_batch = device_put_batch(data_batch)
        rng = jax.random.key(0)

    # chain INGRAPH steps inside one program: per-dispatch host/tunnel
    # latency is amortized and the timed region is device-bound
    def multi_step(state, batch, rng):
        def body(st, r):
            st, metrics = raw_step(st, batch, r)
            return st, metrics["loss"]

        state, losses = jax.lax.scan(
            body, state, jax.random.split(rng, INGRAPH)
        )
        return state, losses[-1]

    # AOT-compile once: the same executable serves warmup, the timed loop,
    # and the FLOPs count for MFU (no second trace/compile)
    with _bench_stage(tracer, phase_prefix + "trace_compile"):
        compiled = jax.jit(multi_step, donate_argnums=0).lower(
            state, dev_batch, rng
        ).compile()

    with _bench_stage(tracer, phase_prefix + "warmup_run"):
        for i in range(WARMUP):
            rng, r = jax.random.split(rng)
            state, loss = compiled(state, dev_batch, r)
        if WARMUP:
            # Sync by fetching the VALUE, not just readiness: over the
            # tunneled backend, block_until_ready has returned before
            # device completion (round-1's withdrawn 44.9M pairs/s and
            # round-4's 1084%-of-peak first record — both physically
            # impossible). A device_get of the chained loss cannot resolve
            # early: the bytes don't exist until the whole scan has run.
            jax.device_get(loss)
        else:
            jax.block_until_ready(state.params)

    # validate the clock itself before trusting the timed region with it
    # (once per process; the flagship run reuses first_light's verdict)
    if (
        os.environ.get("AF2TPU_BENCH_CLOCK_CHECK", "1") != "0"
        and jax.devices()[0].platform != "cpu"
        and _CLOCK["probe"] is None
    ):
        with _bench_stage(tracer, phase_prefix + "clock_probe"):
            _CLOCK["probe"] = _clock_probe()

    with _bench_stage(tracer, phase_prefix + "timed_run"):
        t0 = time.perf_counter()
        for i in range(ITERS):
            rng, r = jax.random.split(rng)
            state, loss = compiled(state, dev_batch, r)
        # one scalar fetch closes the timed region (see warmup comment);
        # its single tunnel round-trip amortizes over ITERS*INGRAPH steps
        # and can only make the measurement conservative, never inflate it
        jax.device_get(loss)
        dt = (time.perf_counter() - t0) / (ITERS * INGRAPH)
    _PHASE["name"] = phase_prefix + "record"

    pairs_per_sec = batch * crop * crop / dt
    mfu = _estimate_mfu(compiled, dt * INGRAPH)

    baseline_path = os.path.join(os.path.dirname(__file__), "bench_baseline.json")
    # env-size overrides AND in-process first-light overrides are both
    # non-flagship configs: never compared against the committed baseline
    overridden = config_overridden() or bool(overrides)
    vs_baseline = 1.0
    compared = False
    if os.path.exists(baseline_path) and not overridden:
        # the committed baseline is the flagship config on TPU; comparing a
        # size-overridden smoke run against it would be meaningless — and so
        # would comparing across timing methodologies (the in-graph step
        # count changes what per-step time includes), hence the ingraph
        # match requirement
        with open(baseline_path) as f:
            base = json.load(f)
        if base.get("value") and base.get("ingraph") == INGRAPH:
            vs_baseline = pairs_per_sec / base["value"]
            compared = True
        elif base.get("value"):
            print(
                f"WARNING: bench_baseline.json was recorded with "
                f"ingraph={base.get('ingraph')} but this run uses "
                f"ingraph={INGRAPH}; regression detection is DISARMED "
                "(vs_baseline=1.0 means 'not compared'). Re-record the "
                "baseline on TPU to re-arm.",
                file=sys.stderr,
            )

    record = {
        "metric": _metric(crop=crop, msa_depth=msa_depth, msa_len=msa_len,
                          dim=dim, depth=depth, batch=batch),
        "value": round(pairs_per_sec, 1),
        "unit": "pairs/sec",
        "vs_baseline": round(vs_baseline, 3),
        "ingraph": INGRAPH,
        # False = no comparable baseline (none committed, size override, or
        # methodology mismatch) — vs_baseline 1.0 then means "not compared",
        # not "at parity"; re-record bench_baseline.json to re-arm
        "vs_baseline_valid": compared,
        # regression-gate comparisons (observe.regress) are device-keyed
        "device": jax.devices()[0].device_kind,
    }
    if mfu is not None:
        record["mfu"] = round(mfu, 4)
    # >100% of the chip's published peak (or, on a chip _PEAK_FLOPS does
    # not know, more than any production chip can sustain): the clock, not
    # the model. Mark the record so nothing downstream (stage_baseline,
    # PARITY/BASELINE claims) can treat it as a valid measurement — the
    # round-1 44.9M pairs/s record was committed unguarded and had to be
    # withdrawn by hand.
    flops = _step_flops(compiled)
    if flops:
        # the INGRAPH-chained program's flop count (cost analysis covers
        # the whole lax.scan, not one step)
        record["program_flops"] = flops
    achieved = (flops / (dt * INGRAPH)) if flops else None
    if (mfu is not None and mfu > 1.0) or (
        mfu is None and achieved is not None
        and achieved > _SANITY_FLOPS_CEILING
    ):
        record["implausible"] = True
        print(
            "WARNING: physically impossible measurement "
            f"(mfu={mfu}, achieved_flops/s={achieved:.3g}) — the timed "
            "region is not syncing with device completion. Record marked "
            "implausible.",
            file=sys.stderr,
        )
    if _CLOCK["probe"] is not None:
        record["clock_probe"] = _CLOCK["probe"]
        if not _CLOCK["probe"]["ok"]:
            # sub-peak inflation the >100%-MFU guard cannot see: the extra
            # in-graph work's elapsed delta implies more than peak FLOPs/s,
            # so the sync is not tracking device completion (ADVICE r4)
            record["clock_suspect"] = True
            print(
                "WARNING: clock probe failed (known extra work implies "
                f"{_CLOCK['probe']['implied_flops_per_s']:.3g} FLOP/s > "
                f"ceiling {_CLOCK['probe']['ceiling_flops_per_s']:.3g}) — "
                "timing does not track device completion. Record marked "
                "clock_suspect.",
                file=sys.stderr,
            )
    if record.get("implausible") or record.get("clock_suspect"):
        # enforce the flag structurally (ADVICE r4): any consumer that
        # ignores the marker keys must still see "no valid comparison"
        record["vs_baseline"] = 0.0
        record["vs_baseline_valid"] = False
    if not overrides and _FIRST_LIGHT["record"] is not None:
        # evidence trail: the flagship line carries its first-light result
        fl = _FIRST_LIGHT["record"]
        record["first_light"] = {
            "metric": fl["metric"], "value": fl["value"],
            **({"mfu": fl["mfu"]} if "mfu" in fl else {}),
            **({"implausible": True} if fl.get("implausible") else {}),
        }
    spans = tracer.span_totals()
    if spans:
        record["spans"] = spans
    hbm_peak = MemorySampler().peak_bytes()
    if hbm_peak is not None:
        record["hbm_peak_bytes"] = hbm_peak
    logger = _metrics_logger()
    if logger is not None:
        logger.log(0, {
            k: v for k, v in record.items()
            if isinstance(v, (int, float, str, bool))
        })
        MemorySampler().log_to(logger)
    if owns_tracer:
        tracer.close()
    if emit:
        _emit(record)
    return record


# ------------------------------------------------------------------ serve ---

# AF2TPU_SERVE_* knobs (NOT AF2TPU_BENCH_*: they must not trip the flagship
# train bench's config_overridden detection). Any of these set -> the serve
# record is a non-flagship config and is never compared to the committed
# serve baseline.
_SERVE_INFRA_KNOBS = {"AF2TPU_SERVE_RECORD_BASELINE"}

# the variant knobs select BETWEEN flagships (single-device vs sharded vs
# bf16 vs tied-row), they do not size-override one: each variant's identity
# rides in the metric label AND its own record key (mesh / dtype / tied
# rows), and the regression gate (observe.regress) refuses any cross-key
# comparison — so records stay self-keyed and safe to compare against their
# own committed baseline (bench_serve_mesh_baseline.json /
# bench_serve_bf16_baseline.json). AF2TPU_KERNELS likewise selects a
# kernel-policy variant: it is not an AF2TPU_SERVE_ size override, and its
# resolved identity rides in the record's "kernels" key.
_SERVE_MESH_KNOBS = {
    "AF2TPU_SERVE_MESH",
    "AF2TPU_SERVE_LONG_BUCKETS",
    "AF2TPU_SERVE_LONG_REQUESTS",
    "AF2TPU_SERVE_DTYPE",
    "AF2TPU_SERVE_TIE_ROWS",
}


def serve_config_overridden() -> bool:
    return any(
        k.startswith("AF2TPU_SERVE_")
        and k not in _SERVE_INFRA_KNOBS
        and k not in _SERVE_MESH_KNOBS
        for k in os.environ
    )


def _serve_sizes() -> dict:
    """The serve-bench flagship config; CPU-mesh sized so tier-1 hosts give
    real (nonzero, clock-honest) numbers — the first valid perf points of
    the trajectory. TPU-scale serving reuses the same engine with bigger
    AF2TPU_SERVE_* values once the tunnel is back.

    ``AF2TPU_SERVE_MESH`` selects the SECOND flagship — sharded serving
    over the long-chain ladder: its own (smaller-trunk, 512-bucket)
    default sizes, its own metric label and its own mesh-keyed committed
    baseline. Both flagships are fully default-defined; any size env on
    top marks the record overridden exactly as before."""
    mesh_spec = os.environ.get("AF2TPU_SERVE_MESH", "")
    # (single-device flagship default, mesh flagship default)
    dflt = {
        "buckets": ("32,48,64", "32,64"),
        "max_batch": (4, 2),
        "requests": (24, 8),
        "dim": (64, 16),
        "depth": (2, 1),
        "heads": (4, 1),
        "dim_head": (16, 8),
        "msa_depth": (4, 2),
        "mds_iters": (50, 20),
        "long_buckets": ("", "512"),
    }
    pick = 1 if mesh_spec else 0

    buckets = tuple(
        int(v) for v in os.environ.get(
            "AF2TPU_SERVE_BUCKETS", dflt["buckets"][pick]
        ).split(",") if v
    )
    long_buckets = tuple(
        int(v) for v in os.environ.get(
            "AF2TPU_SERVE_LONG_BUCKETS", dflt["long_buckets"][pick]
        ).split(",") if v
    )
    return {
        "buckets": buckets,
        "max_batch": _env_int("AF2TPU_SERVE_MAX_BATCH", dflt["max_batch"][pick]),
        "requests": _env_int("AF2TPU_SERVE_REQUESTS", dflt["requests"][pick]),
        "dim": _env_int("AF2TPU_SERVE_DIM", dflt["dim"][pick]),
        "depth": _env_int("AF2TPU_SERVE_DEPTH", dflt["depth"][pick]),
        "heads": _env_int("AF2TPU_SERVE_HEADS", dflt["heads"][pick]),
        "dim_head": _env_int("AF2TPU_SERVE_DIM_HEAD", dflt["dim_head"][pick]),
        "msa_depth": _env_int("AF2TPU_SERVE_MSA_DEPTH", dflt["msa_depth"][pick]),
        "mds_iters": _env_int("AF2TPU_SERVE_MDS_ITERS", dflt["mds_iters"][pick]),
        "seed": _env_int("AF2TPU_SERVE_SEED", 0),
        # the sharded serve flagship: a mesh spec ("1x2x4" = dp x spr x
        # spc grid) opens the mesh-gated long-chain rungs and routes the
        # record to the mesh-keyed baseline
        "mesh": mesh_spec,
        "long_buckets": long_buckets,
        "long_requests": _env_int("AF2TPU_SERVE_LONG_REQUESTS", 1),
        # precision/workload variants (not size overrides): bf16 serving
        # routes to its own dtype-keyed baseline; tied rows turn on the
        # MSA tied-row attention path (the tied-row kernel's shape)
        "dtype": os.environ.get("AF2TPU_SERVE_DTYPE", "float32"),
        "tie_rows": _env_int("AF2TPU_SERVE_TIE_ROWS", 0) != 0,
    }


def _serve_metric(s: dict) -> str:
    label = (
        f"serve residues/sec buckets={','.join(map(str, s['buckets']))} "
        f"max_batch={s['max_batch']} requests={s['requests']} "
        f"dim={s['dim']} depth={s['depth']} msa_depth={s['msa_depth']} "
        f"mds_iters={s['mds_iters']}"
    )
    if s.get("mesh"):
        # the sharded flagship is a DIFFERENT metric (and baseline): the
        # mesh and long-chain workload are part of what is measured
        label += (
            f" mesh={s['mesh']} "
            f"long={','.join(map(str, s['long_buckets'])) or '-'}"
            f"x{s['long_requests']}"
        )
    if s.get("dtype", "float32") != "float32":
        # the precision variant is likewise its own metric (and baseline)
        label += f" dtype={s['dtype']}"
    if s.get("tie_rows"):
        label += " tied_rows"
    return label


def bench_serve(emit: bool = True, tracer: Tracer | None = None) -> dict:
    """Serving throughput/latency on the bucketed batched engine.

    Measures a mixed-length request stream end to end: residues/sec over
    the whole stream plus p50/p95/p99 per-request latency from the
    engine's streaming Histogram (queue wait + dispatch — what a caller
    observes), with queue-wait/dispatch/batch-occupancy/pad-ratio
    distributions and per-stage span timings alongside. Compiles happen
    in an explicit warmup and are reported separately (per-(bucket,batch)
    durations in ``compile_records``); the timed region closes on
    jax.device_get of the output coordinates, so the numbers are real
    completions, not dispatch acks (clock-probe-checked on non-CPU
    backends like the main bench)."""
    import numpy as np

    from alphafold2_tpu.config import (
        Config, DataConfig, ModelConfig, ServeConfig,
    )
    from alphafold2_tpu.serve import ServeEngine, ServeRequest, padding_fraction

    owns_tracer = tracer is None
    tracer = tracer if tracer is not None else _tracer()
    if not tracer.enabled:
        # device_idle_frac is computed from live serve.dispatch /
        # serve.device_get spans, so the headline run always traces (a
        # memory-only tracer when no trace file was requested)
        tracer = Tracer(enabled=True)
        owns_tracer = True
    s = _serve_sizes()
    with _bench_stage(tracer, "serve:backend_init"):
        from alphafold2_tpu.parallel.sharding import parse_mesh_spec

        mesh = parse_mesh_spec(s["mesh"])
        top = (s["long_buckets"] or s["buckets"])[-1]
        cfg = Config(
            model=ModelConfig(
                dim=s["dim"], depth=s["depth"], heads=s["heads"],
                dim_head=s["dim_head"], max_seq_len=3 * top,
                bfloat16=jax.devices()[0].platform != "cpu",
                # the tied-rows variant exercises the tied-row MSA
                # attention path (the tied-row kernel's shape)
                msa_tie_row_attn=s["tie_rows"],
                # a grid mesh needs the sharded axial primitive (the
                # engine refuses the combination otherwise)
                grid_parallel=bool(
                    mesh is not None and "spr" in mesh.axis_names
                ),
            ),
            data=DataConfig(msa_depth=s["msa_depth"]),
            serve=ServeConfig(
                buckets=s["buckets"], max_batch=s["max_batch"],
                mds_iters=s["mds_iters"],
                long_buckets=s["long_buckets"] if mesh is not None else (),
                dtype=s["dtype"],
            ),
        )
        engine = ServeEngine(cfg, tracer=tracer, mesh=mesh)

    # deterministic mixed-length request stream spanning the ladder
    rng = np.random.default_rng(s["seed"])
    lo = max(4, s["buckets"][0] // 2)
    lengths = rng.integers(lo, s["buckets"][-1] + 1, size=s["requests"])
    alpha = "ACDEFGHIKLMNPQRSTVWY"
    reqs = [
        ServeRequest(
            seq="".join(rng.choice(list(alpha), size=int(n))), seed=i
        )
        for i, n in enumerate(lengths)
    ]
    if mesh is not None and s["long_buckets"]:
        # the crop-free long-chain workload: requests near the top rung —
        # lengths a single device REJECTS (the mesh-gated ladder), served
        # here because the pair grid is sharded O(N^2/(spr*spc)) per device
        for i in range(s["long_requests"]):
            n = int(s["long_buckets"][-1] * 0.92) + i
            reqs.append(ServeRequest(
                seq="".join(rng.choice(list(alpha), size=n)),
                seed=len(reqs),
            ))

    with _bench_stage(tracer, "serve:trace_compile"):
        t0 = time.perf_counter()
        engine.warmup()  # one executable per ladder rung, counted
        compile_s = time.perf_counter() - t0

    if (
        os.environ.get("AF2TPU_BENCH_CLOCK_CHECK", "1") != "0"
        and jax.devices()[0].platform != "cpu"
        and _CLOCK["probe"] is None
    ):
        with _bench_stage(tracer, "serve:clock_probe"):
            _CLOCK["probe"] = _clock_probe()

    with _bench_stage(tracer, "serve:timed_run"):
        flops_before = engine.executed_flops
        t0 = time.perf_counter()
        results = engine.predict_many(reqs)
        wall = time.perf_counter() - t0
        executed_flops = engine.executed_flops - flops_before
    _PHASE["name"] = "serve:record"
    # host/device overlap over the timed stream, measured from the spans
    # the dispatch path just emitted (warmup compiles emit none of the
    # device-span names, so the window covers exactly the stream)
    idle = device_idle_fraction(tracer.events())

    total_residues = int(sum(len(r.seq) for r in reqs))
    assert all(r is not None for r in results)
    stats = engine.stats()
    hists = {  # time histograms scaled seconds -> ms, renamed to match
        (n[:-2] + "_ms" if n.endswith("_s") else n): snap
        for n, snap in engine.histogram_snapshots(unit_scale=1e3).items()
    }
    lat = hists["latency_ms"]

    record = {
        "metric": _serve_metric(s),
        "value": round(total_residues / wall, 1),
        "unit": "residues/sec",
        "mode": "serve",
        # per-request latency percentiles from the streaming Histogram
        # (queue wait + dispatch, ms)
        "p50_ms": round(lat["p50"], 1),
        "p95_ms": round(lat["p95"], 1),
        "p99_ms": round(lat["p99"], 1),
        "compile_s": round(compile_s, 1),
        "compiles": stats.get("serve.compiles", 0),
        "cache_hits": stats.get("serve.cache_hits", 0),
        "requests": stats.get("serve.requests", 0),
        "batches": stats.get("serve.batches", 0),
        "padding_fraction": round(
            padding_fraction(
                # the engine's effective ladder includes the admitted
                # long-chain rungs
                [len(r.seq) for r in reqs], engine.buckets,
            ), 3,
        ),
        # queue-wait/dispatch breakdown + occupancy/pad distributions
        "histograms": hists,
        # XLA build durations keyed by executable shape
        "compile_records": engine.compile_records,
        "device": jax.devices()[0].device_kind,
        # dispatch-path variant key: pipelined ("depthN") vs serial
        # ("off") numbers are different measurements — the regression
        # gate refuses any cross-key comparison (observe.regress)
        "pipeline": engine.pipeline_desc,
        # precision/kernel variant keys, present only when non-default so
        # pre-existing baselines stay comparable; the regression gate
        # refuses any cross-key comparison (observe.regress)
        **({"dtype": engine.serve_dtype}
           if engine.serve_dtype != "float32" else {}),
        **({"kernels": engine.kernels_desc}
           if engine.kernels_desc != "auto" else {}),
    }
    if idle is not None:
        # fraction of the dispatch window the device spent NOT inside a
        # serve.dispatch/serve.device_get span — the overlap the pipeline
        # buys, gated as an absolute ceiling by observe/regress.py
        record["device_idle_frac"] = round(idle["device_idle_frac"], 4)
        record["device_idle"] = {
            "busy_s": round(idle["busy_s"], 3),
            "window_s": round(idle["window_s"], 3),
            "dispatches": idle["dispatches"],
        }
    if mesh is not None:
        # mesh-keyed record: the identity string keys the executable
        # cache, the result cache, the baseline file and the regression
        # gate's comparability check all at once
        record["mesh"] = engine.mesh_desc
        record["mesh_devices"] = int(mesh.devices.size)
        per_dev = [
            c["program_bytes"] for c in engine.compile_records
            if c.get("program_bytes")
        ]
        if per_dev:
            # XLA memory analysis is per device for SPMD programs — the
            # quantity the pair-grid sharding shrinks, gated vs baseline
            record["per_device_program_bytes"] = max(per_dev)
    if executed_flops:
        # dispatched model flops over the timed stream (observe.flops)
        record["flops_total"] = executed_flops
        if engine.executed_flops_breakdown:
            # analytical per-kernel attribution (tied-row vs axial vs
            # rest): an MFU delta names the attention family responsible
            record["flops_by_kernel"] = {
                k: round(v, 1)
                for k, v in engine.executed_flops_breakdown.items()
            }
        if mesh is not None:
            from alphafold2_tpu.observe.flops import mesh_mfu as _mesh_mfu

            m = _mesh_mfu(executed_flops, wall, mesh=mesh)
            if m.get("mfu") is not None:
                record["mfu"] = round(m["mfu"], 4)
                record["mfu_basis"] = m["mfu_basis"]
        else:
            from alphafold2_tpu.observe.flops import mfu as _mfu

            serve_mfu = _mfu(executed_flops, wall)
            if serve_mfu is not None:
                record["mfu"] = round(serve_mfu, 4)
    spans = tracer.span_totals()
    if spans:
        record["spans"] = spans
    hbm_peak = engine.memory.peak_bytes()
    if hbm_peak is not None:
        record["hbm_peak_bytes"] = hbm_peak
    if _CLOCK["probe"] is not None:
        record["clock_probe"] = _CLOCK["probe"]
        if not _CLOCK["probe"]["ok"]:
            record["clock_suspect"] = True

    # the serve trajectory competes against its own committed first record,
    # like the train bench; comparisons require the identical metric label
    # AND device AND mesh (a CPU-mesh number vs a TPU number is not a
    # comparison, nor is a sharded number vs a single-device one) — the
    # sharded flagship gets its own mesh-keyed baseline file
    baseline_path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "bench_serve_mesh_baseline.json" if mesh is not None
        else "bench_serve_bf16_baseline.json"
        if engine.serve_dtype == "bfloat16"
        else "bench_serve_baseline.json",
    )
    vs, compared = 1.0, False
    if (
        os.path.exists(baseline_path)
        and not serve_config_overridden()
        and not record.get("clock_suspect")
    ):
        with open(baseline_path) as f:
            base = json.load(f)
        if (
            base.get("value")
            and base.get("metric") == record["metric"]
            and base.get("device") == record["device"]
            # kernel policy and dispatch-path pipelining are variant keys
            # the metric label does not encode: a different selection is
            # a different measurement
            and base.get("kernels") == record.get("kernels")
            and base.get("pipeline") == record.get("pipeline")
        ):
            vs = record["value"] / base["value"]
            compared = True
    record["vs_baseline"] = round(vs, 3)
    record["vs_baseline_valid"] = compared and not record.get("clock_suspect")
    if record.get("clock_suspect"):
        record["vs_baseline"] = 0.0

    if (
        os.environ.get("AF2TPU_SERVE_RECORD_BASELINE") == "1"
        and not serve_config_overridden()
        and not record.get("clock_suspect")
    ):
        with open(baseline_path, "w") as f:
            json.dump(record, f, indent=2)
        print(f"recorded serve baseline -> {baseline_path}", file=sys.stderr)

    logger = _metrics_logger()
    if logger is not None:
        logger.log(0, stats)
        logger.log(0, {
            k: v for k, v in record.items()
            if isinstance(v, (int, float, str, bool))
        })
        # mesh runs log per-device HBM peaks (obs_report's mesh section)
        MemorySampler().log_to(logger, per_device=mesh is not None)
    if owns_tracer:
        tracer.close()
    if emit:
        _emit(record)
    return record


# ------------------------------------------------------------ serve-async ---


def _serve_async_sizes() -> dict:
    """The open-loop serve-async flagship; CPU-mesh sized (tiny trunk,
    short buckets) so CI runners and tier-1 hosts produce comparable
    records against the committed ``bench_serve_async_baseline.json``.
    AF2TPU_SERVE_ASYNC_* env knobs rescale it for TPU sessions — any of
    them set marks the record non-flagship (never baseline-compared)."""
    buckets = tuple(
        int(v) for v in os.environ.get(
            "AF2TPU_SERVE_ASYNC_BUCKETS", "12,16,24"
        ).split(",") if v
    )
    return {
        "buckets": buckets,
        "max_batch": _env_int("AF2TPU_SERVE_ASYNC_MAX_BATCH", 4),
        "requests": _env_int("AF2TPU_SERVE_ASYNC_REQUESTS", 50),
        "rate": float(os.environ.get("AF2TPU_SERVE_ASYNC_RATE", 8.0)),
        "dup_fraction": 0.2,  # workload definition: repeat-sequence share
        "dim": _env_int("AF2TPU_SERVE_ASYNC_DIM", 32),
        "depth": _env_int("AF2TPU_SERVE_ASYNC_DEPTH", 1),
        "heads": _env_int("AF2TPU_SERVE_ASYNC_HEADS", 2),
        "dim_head": _env_int("AF2TPU_SERVE_ASYNC_DIM_HEAD", 16),
        "msa_depth": _env_int("AF2TPU_SERVE_ASYNC_MSA_DEPTH", 2),
        "mds_iters": _env_int("AF2TPU_SERVE_ASYNC_MDS_ITERS", 20),
        "dwell_ms": float(os.environ.get("AF2TPU_SERVE_ASYNC_DWELL_MS", 30.0)),
        "queue_depth": _env_int("AF2TPU_SERVE_ASYNC_QUEUE_DEPTH", 16),
        "deadline_s": float(
            os.environ.get("AF2TPU_SERVE_ASYNC_DEADLINE_S", 30.0)
        ),
        "cache_size": _env_int("AF2TPU_SERVE_ASYNC_CACHE", 64),
        "seed": _env_int("AF2TPU_SERVE_ASYNC_SEED", 0),
        # workload definition like dup_fraction: the priority-class mix
        # (high/normal/low shares) the per-class latency breakdowns and
        # per-class SLO specs are evaluated over
        "class_mix": (0.2, 0.6, 0.2),
    }


def _serve_async_metric(s: dict) -> str:
    mix = "/".join(f"{v:g}" for v in s["class_mix"])
    return (
        f"serve-async residues/sec buckets={','.join(map(str, s['buckets']))} "
        f"max_batch={s['max_batch']} requests={s['requests']} "
        f"rate={s['rate']:g}/s dup={s['dup_fraction']:g} classes={mix} "
        f"dim={s['dim']} "
        f"depth={s['depth']} msa_depth={s['msa_depth']} "
        f"mds_iters={s['mds_iters']} dwell_ms={s['dwell_ms']:g} "
        f"queue={s['queue_depth']} deadline_s={s['deadline_s']:g}"
    )


def _telemetry_overhead_probe(engine, s: dict, arms: int = 2,
                              n_requests: int = 12) -> dict:
    """The telemetry plane's cost, measured: identical closed-loop bursts
    through fresh frontends against the ALREADY-WARM engine, alternating
    telemetry off (disabled tracer, no observers) and on (memory tracer +
    SLO monitor + registry feed), best-of-``arms`` per arm so a one-off
    scheduler hiccup doesn't fake an overhead. The burst stays under the
    queue depth at high priority, so admission control never varies
    between arms."""
    import numpy as np

    from alphafold2_tpu.observe.registry import MetricsRegistry
    from alphafold2_tpu.observe.slo import SLOMonitor, default_serve_slos
    from alphafold2_tpu.serve import AsyncServeFrontend, ServeRequest

    rng = np.random.default_rng(s["seed"] + 1)
    lo = max(4, s["buckets"][0] // 2)
    alpha = "ACDEFGHIKLMNPQRSTVWY"
    n = max(1, min(n_requests, s["queue_depth"] - 2))
    seqs = [
        "".join(rng.choice(
            list(alpha), size=int(rng.integers(lo, s["buckets"][-1] + 1))
        ))
        for _ in range(n)
    ]

    def run(telemetry: bool) -> float:
        tr = Tracer(enabled=telemetry)  # memory-only when on
        old_engine_tracer = engine.tracer
        engine.tracer = tr  # the engine's serve.* spans are part of the cost
        try:
            fe = AsyncServeFrontend(engine, tracer=tr)
            if telemetry:
                mon = SLOMonitor(
                    default_serve_slos(s["deadline_s"]),
                    registry=MetricsRegistry(), tracer=tr,
                )
                fe.add_observer(mon.observe)
            t0 = time.perf_counter()
            handles = [
                fe.submit(ServeRequest(seq=q, seed=j, priority=1))
                for j, q in enumerate(seqs)
            ]
            n_ok = sum(
                1 for h in handles if h.result(timeout=600).status == "ok"
            )
            wall = time.perf_counter() - t0
            fe.close()
            return n_ok / wall if wall > 0 else 0.0
        finally:
            engine.tracer = old_engine_tracer

    best = {"off": 0.0, "on": 0.0}
    for _ in range(max(1, arms)):
        for name, tel in (("off", False), ("on", True)):
            best[name] = max(best[name], run(tel))
    frac = (
        max(0.0, 1.0 - best["on"] / best["off"]) if best["off"] else 0.0
    )
    return {
        "goodput_rps_off": round(best["off"], 3),
        "goodput_rps_on": round(best["on"], 3),
        "requests_per_arm": n,
        "arms": arms,
        "overhead_frac": round(frac, 4),
    }


def bench_serve_async(emit: bool = True, tracer: Tracer | None = None) -> dict:
    """Open-loop latency/goodput bench on the async serving frontend.

    A seeded Poisson arrival process (exponential inter-arrival gaps at
    ``rate`` req/s, ~20% repeat sequences) submits requests to an
    ``AsyncServeFrontend`` on their own schedule — the caller does NOT
    wait for one request before offering the next, so queueing, admission
    control, dwell-vs-fill batching, dedup and deadlines are all actually
    exercised. The record carries p50/p95/p99 end-to-end latency over
    successful requests, goodput (ok residues/sec and ok requests/sec over
    the whole open-loop window), the rejection rate, and the structured
    failure counts (deadline misses, cache hits, in-flight dedups,
    retries, dispatch errors). ``AF2TPU_SERVE_ASYNC_FAULT`` (e.g.
    ``"dispatch=2,times=1"``) injects a FaultPlan for degradation drills —
    like every AF2TPU_SERVE_* knob it marks the record non-flagship.

    The telemetry plane is ALWAYS on for the headline run (a memory-only
    tracer when $AF2TPU_TRACE_EVENTS is unset): the record carries the
    trace-reconstruction completeness fraction over non-rejected requests,
    per-priority-class latency/goodput breakdowns, SLO burn-rate verdicts
    (``AF2TPU_SLO_SPECS`` overrides the default specs), and a measured
    telemetry-on-vs-off overhead fraction — the last two gated by
    ``observe/regress.py``'s absolute thresholds."""
    import numpy as np

    from alphafold2_tpu.config import (
        Config, DataConfig, ModelConfig, ServeConfig,
    )
    from alphafold2_tpu.observe import Histogram
    from alphafold2_tpu.observe.registry import MetricsRegistry
    from alphafold2_tpu.observe.slo import (
        SLOMonitor, default_serve_slos, parse_slo_specs, priority_class,
    )
    from alphafold2_tpu.observe.tracectx import trace_completeness
    from alphafold2_tpu.observe.workload import WorkloadRecorder
    from alphafold2_tpu.serve import (
        AsyncServeFrontend, FaultPlan, ServeEngine, ServeRequest,
    )

    owns_tracer = tracer is None
    tracer = tracer if tracer is not None else _tracer()
    if not tracer.enabled:
        # the telemetry contract (trace completeness, SLO ingestion) needs
        # live events even when no trace file was requested
        tracer = Tracer(enabled=True)
        owns_tracer = True
    rec_fr = flightrec.maybe_install_from_env()
    if rec_fr is not None:
        rec_fr.attach(tracer)
    s = _serve_async_sizes()
    with _bench_stage(tracer, "serve_async:backend_init"):
        cfg = Config(
            model=ModelConfig(
                dim=s["dim"], depth=s["depth"], heads=s["heads"],
                dim_head=s["dim_head"], max_seq_len=3 * s["buckets"][-1],
                bfloat16=jax.devices()[0].platform != "cpu",
            ),
            data=DataConfig(msa_depth=s["msa_depth"]),
            serve=ServeConfig(
                buckets=s["buckets"], max_batch=s["max_batch"],
                mds_iters=s["mds_iters"], dwell_ms=s["dwell_ms"],
                queue_depth=s["queue_depth"],
                default_deadline_s=s["deadline_s"],
                cache_size=s["cache_size"],
            ),
        )
        faults = FaultPlan.from_spec(
            os.environ.get("AF2TPU_SERVE_ASYNC_FAULT")
        )
        engine = ServeEngine(cfg, tracer=tracer, faults=faults)

    # deterministic open-loop workload: Poisson arrivals, mixed lengths,
    # ~dup_fraction repeats of earlier (seq, seed) pairs (cache/dedup
    # food), priorities drawn from class_mix. A repeat is a FRESH request
    # object with the same (seq, seed): its own arrival, priority, and
    # trace identity — two users submitting the same sequence are two
    # lifecycles that happen to share one dispatch
    rng = np.random.default_rng(s["seed"])
    lo = max(4, s["buckets"][0] // 2)
    alpha = "ACDEFGHIKLMNPQRSTVWY"
    pri_levels = np.array([1, 0, -1])
    reqs: list = []
    for i in range(s["requests"]):
        priority = int(rng.choice(pri_levels, p=np.array(s["class_mix"])))
        if reqs and rng.random() < s["dup_fraction"]:
            src = reqs[int(rng.integers(0, len(reqs)))]
            reqs.append(ServeRequest(
                seq=src.seq, seed=src.seed, priority=priority
            ))
        else:
            n = int(rng.integers(lo, s["buckets"][-1] + 1))
            reqs.append(ServeRequest(
                seq="".join(rng.choice(list(alpha), size=n)), seed=i,
                priority=priority,
            ))
    gaps = rng.exponential(1.0 / s["rate"], size=s["requests"])

    with _bench_stage(tracer, "serve_async:trace_compile"):
        t0 = time.perf_counter()
        engine.warmup()  # one executable per ladder rung, counted
        compile_s = time.perf_counter() - t0

    if (
        os.environ.get("AF2TPU_BENCH_CLOCK_CHECK", "1") != "0"
        and jax.devices()[0].platform != "cpu"
        and _CLOCK["probe"] is None
    ):
        with _bench_stage(tracer, "serve_async:clock_probe"):
            _CLOCK["probe"] = _clock_probe()

    # telemetry plane around the timed run: SLO monitor + rolling-window
    # registry fed from every resolution, periodic snapshots to the JSONL
    # channel (and the flight recorder), optional Prometheus exposition
    logger = _metrics_logger()
    registry = MetricsRegistry()
    slo_specs = parse_slo_specs(
        os.environ.get("AF2TPU_SLO_SPECS", "")
    ) or default_serve_slos(s["deadline_s"])
    slo_monitor = SLOMonitor(slo_specs, registry=registry, tracer=tracer)

    def _feed_registry(result, priority):
        registry.windowed_counter(f"serve.resolved.{result.status}").add()
        if result.status == "ok":
            registry.windowed_values(
                f"serve.latency_ms.{priority_class(priority)}"
            ).observe(result.latency_s * 1e3)

    frontend = AsyncServeFrontend(engine, tracer=tracer)
    frontend.add_observer(slo_monitor.observe)
    frontend.add_observer(_feed_registry)
    # workload capture (observe/workload.py): every submit + resolution as
    # a scrubbed event — ring-only by default (the flight recorder's
    # workload tail), a replayable JSONL artifact when AF2TPU_WORKLOAD_LOG
    # is set (raw sequences only with AF2TPU_WORKLOAD_RAW=1; the bench's
    # own traffic is synthetic, so the CI smoke opts in)
    workload_rec = WorkloadRecorder(
        path=os.environ.get("AF2TPU_WORKLOAD_LOG"),
        record_raw=os.environ.get("AF2TPU_WORKLOAD_RAW") == "1",
        buckets=s["buckets"], msa_depth=s["msa_depth"],
    )
    frontend.add_submit_observer(workload_rec.on_submit)
    frontend.add_observer(workload_rec.observe)
    if rec_fr is not None:
        rec_fr.attach_workload(workload_rec.tail)
    # zero-seed the variant-scan counters so the fleet scrape sees the
    # gauges (as 0) even before the first family/feature-cache event —
    # EventCounters.snapshot() omits never-bumped keys, and an absent
    # series is indistinguishable from a dead exporter to a scraper
    _scan_counter_zeros = {
        "serve.feat_hits": 0, "serve.feat_delta": 0,
        "serve.feat_misses": 0, "sched.family_members": 0,
        "sched.affinity_batches": 0, "sched.family_inflight_joins": 0,
    }
    metrics_server = exposition.serve_from_env(
        lambda: {
            **_scan_counter_zeros,
            **engine.counters.snapshot(),
            **registry.snapshot(),
        }
    )
    registry.start_snapshotter(
        logger, period_s=0.5,
        also=(
            (lambda snap: rec_fr.snapshot("registry", snap))
            if rec_fr is not None else None
        ),
    )
    with _bench_stage(tracer, "serve_async:timed_run"):
        t0 = time.perf_counter()
        handles = []
        due = t0
        for req, gap in zip(reqs, gaps):
            due += gap
            delay = due - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
            handles.append(frontend.submit(req))
        results = [h.result(timeout=600) for h in handles]
        wall = time.perf_counter() - t0
    frontend.close()
    registry.stop_snapshotter()
    slo_verdicts = slo_monitor.evaluate()
    _PHASE["name"] = "serve_async:record"

    ok = [r for r in results if r.status == "ok"]
    rejected = sum(1 for r in results if r.status == "rejected")
    deadline_missed = sum(
        1 for r in results if r.status == "deadline_exceeded"
    )
    errors = sum(1 for r in results if r.status == "error")
    lat = Histogram()
    for r in ok:
        lat.observe(r.latency_s)
    lat_ms = lat.snapshot(unit_scale=1e3, digits=4) if ok else {"count": 0}
    stats = frontend.stats()

    # per-priority-class breakdown: what the per-class SLO specs promise,
    # and what the per-class regression thresholds gate
    class_acc: dict = {}
    for req, r in zip(reqs, results):
        b = class_acc.setdefault(
            priority_class(req.priority),
            {"requests": 0, "completed": 0, "rejected": 0,
             "hist": Histogram()},
        )
        b["requests"] += 1
        if r.status == "ok":
            b["completed"] += 1
            b["hist"].observe(r.latency_s)
        elif r.status == "rejected":
            b["rejected"] += 1
    by_class = {}
    for cls, b in sorted(class_acc.items()):
        snap = (
            b["hist"].snapshot(unit_scale=1e3, digits=4)
            if b["completed"] else {"count": 0}
        )
        by_class[cls] = {
            "requests": b["requests"],
            "completed": b["completed"],
            "rejected": b["rejected"],
            "goodput_rps": round(b["completed"] / wall, 3),
            "p50_ms": round(snap.get("p50", 0.0), 1),
            "p95_ms": round(snap.get("p95", 0.0), 1),
            "p99_ms": round(snap.get("p99", 0.0), 1),
        }

    # per-request cost ledger (ServeResult.cost) rolled up per priority
    # class and per (hashed) family: where the device-seconds, amortized
    # compile and padding actually went — the substrate the cost-aware
    # tiering item needs per-tier
    _cost_keys = ("queue_wait_s", "device_share_s", "compile_share_s",
                  "flops_share", "pad_fraction")

    def _cost_add(acc: dict, cost: dict) -> None:
        acc["n"] += 1
        for k in _cost_keys:
            acc[k] += cost.get(k, 0.0)

    def _cost_round(acc: dict) -> dict:
        out = {"n": acc["n"]}
        for k in _cost_keys:
            total = acc[k]
            # padding is only meaningful as a mean; the rest as totals
            out[k] = round(
                total / max(1, acc["n"]) if k == "pad_fraction" else total,
                6,
            )
        return out

    fam_map = workload_rec.family_by_trace()
    cost_by_class: dict = {}
    cost_by_family: dict = {}
    for req, r in zip(reqs, results):
        if not r.cost:
            continue
        acc = cost_by_class.setdefault(
            priority_class(req.priority), {"n": 0, **dict.fromkeys(_cost_keys, 0.0)}
        )
        _cost_add(acc, r.cost)
        fam = fam_map.get(r.trace_id)
        if fam:
            _cost_add(cost_by_family.setdefault(
                fam, {"n": 0, **dict.fromkeys(_cost_keys, 0.0)}
            ), r.cost)
    cost_by_class = {
        cls: _cost_round(acc) for cls, acc in sorted(cost_by_class.items())
    }
    # bounded: the largest families only (a scan-heavy stream could mint
    # hundreds of one-off labels and bloat the record)
    cost_by_family = {
        fam: _cost_round(acc)
        for fam, acc in sorted(
            cost_by_family.items(), key=lambda kv: -kv[1]["n"]
        )[:8]
    }

    # trace reconstruction: every non-rejected request's lifecycle must
    # rebuild from the emitted events as an unbroken span chain
    completeness = trace_completeness(
        tracer.events(),
        [r.trace_id for r in results
         if r.status != "rejected" and r.trace_id],
    )
    # host/device overlap snapshot BEFORE the overhead probe below: the
    # probe issues extra dispatches that would pollute the idle window
    idle = device_idle_fraction(tracer.events())

    with _bench_stage(tracer, "serve_async:overhead_probe"):
        overhead = _telemetry_overhead_probe(engine, s)
    hists = {
        (n[:-2] + "_ms" if n.endswith("_s") else n): snap
        for n, snap in {
            **engine.histogram_snapshots(unit_scale=1e3),
            **frontend.histogram_snapshots(unit_scale=1e3),
        }.items()
    }
    hists["latency_e2e_ms"] = lat_ms

    record = {
        "metric": _serve_async_metric(s),
        "value": round(sum(len(r.seq) for r in ok) / wall, 1),
        "unit": "residues/sec",
        "mode": "serve-async",
        # end-to-end (submit -> resolve) latency over successful requests
        "p50_ms": round(lat_ms.get("p50", 0.0), 1),
        "p95_ms": round(lat_ms.get("p95", 0.0), 1),
        "p99_ms": round(lat_ms.get("p99", 0.0), 1),
        "goodput_rps": round(len(ok) / wall, 3),
        "rejection_rate": round(rejected / max(1, len(results)), 4),
        "requests": len(results),
        "completed": len(ok),
        "rejected": rejected,
        "deadline_misses": deadline_missed,
        "dispatch_error_results": errors,
        "cache_hits": stats.get("sched.cache_hits", 0),
        "inflight_dedup": stats.get("sched.inflight_dedup", 0),
        "retries": stats.get("sched.retries", 0),
        "dispatches": stats.get("sched.dispatches", 0),
        "compiles": stats.get("serve.compiles", 0),
        "compile_s": round(compile_s, 1),
        "histograms": hists,
        "compile_records": engine.compile_records,
        "device": jax.devices()[0].device_kind,
        # dispatch-path variant key (see bench_serve): "depthN" or "off"
        "pipeline": engine.pipeline_desc,
        "by_class": by_class,
        "cost_by_class": cost_by_class,
        **({"cost_by_family": cost_by_family} if cost_by_family else {}),
        "trace": completeness,
        "trace_complete_fraction": completeness["fraction"],
        "slo": slo_verdicts,
        "slo_alerts": sum(1 for v in slo_verdicts if v["alert"]),
        "telemetry_overhead": overhead,
        "telemetry_overhead_frac": overhead["overhead_frac"],
    }
    if idle is not None:
        # open-loop idleness is dominated by the arrival process, so its
        # absolute ceiling (observe/regress.py) is far looser than the
        # closed-loop serve bench's
        record["device_idle_frac"] = round(idle["device_idle_frac"], 4)
        record["device_idle"] = {
            "busy_s": round(idle["busy_s"], 3),
            "window_s": round(idle["window_s"], 3),
            "dispatches": idle["dispatches"],
        }
    # flat per-class keys beside the nested breakdown: the regression
    # gate's threshold table addresses record keys by name
    for cls, b in by_class.items():
        record[f"p95_ms_{cls}"] = b["p95_ms"]
        record[f"goodput_rps_{cls}"] = b["goodput_rps"]
    if metrics_server is not None:
        record["metrics_port"] = metrics_server.port
    if rec_fr is not None and (
        os.environ.get("AF2TPU_FLIGHTREC_FORCE_DUMP") == "1"
    ):
        dump_path = rec_fr.dump("forced", force=True)
        if dump_path:
            record["flightrec_dump"] = dump_path
    if engine.executed_flops:
        record["flops_total"] = engine.executed_flops
        from alphafold2_tpu.observe.flops import mfu as _mfu

        async_mfu = _mfu(engine.executed_flops, wall)
        if async_mfu is not None:
            record["mfu"] = round(async_mfu, 4)
    spans = tracer.span_totals()
    if spans:
        record["spans"] = spans
    hbm_peak = engine.memory.peak_bytes()
    if hbm_peak is not None:
        record["hbm_peak_bytes"] = hbm_peak
    if _CLOCK["probe"] is not None:
        record["clock_probe"] = _CLOCK["probe"]
        if not _CLOCK["probe"]["ok"]:
            record["clock_suspect"] = True

    baseline_path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "bench_serve_async_baseline.json",
    )
    vs, compared = 1.0, False
    if (
        os.path.exists(baseline_path)
        and not serve_config_overridden()
        and not record.get("clock_suspect")
    ):
        with open(baseline_path) as f:
            base = json.load(f)
        if (
            base.get("value")
            and base.get("metric") == record["metric"]
            and base.get("device") == record["device"]
            # pipelined vs serial dispatch are different measurements
            and base.get("pipeline") == record.get("pipeline")
        ):
            vs = record["value"] / base["value"]
            compared = True
    record["vs_baseline"] = round(vs, 3)
    record["vs_baseline_valid"] = compared and not record.get("clock_suspect")
    if record.get("clock_suspect"):
        record["vs_baseline"] = 0.0

    if (
        os.environ.get("AF2TPU_SERVE_RECORD_BASELINE") == "1"
        and not serve_config_overridden()
        and not record.get("clock_suspect")
    ):
        with open(baseline_path, "w") as f:
            json.dump(record, f, indent=2)
        print(
            f"recorded serve-async baseline -> {baseline_path}",
            file=sys.stderr,
        )

    if logger is not None:
        logger.log(0, stats)
        logger.log(0, {
            k: v for k, v in record.items()
            if isinstance(v, (int, float, str, bool))
        })
        for v in slo_verdicts:  # slo/<spec>/<field> keys for obs_report
            logger.log(0, {
                f"slo/{v['spec']}/{k}": val for k, val in v.items()
                if isinstance(val, (int, float, bool))
            })
        MemorySampler().log_to(logger)
    # the recording's closing summary: the reference half of the
    # record→replay diff (--mode serve-replay loads it via load_workload)
    workload_rec.write_summary({
        "requests": len(results),
        "completed": len(ok),
        "goodput_rps": record["goodput_rps"],
        "p50_ms": record["p50_ms"],
        "p95_ms": record["p95_ms"],
        "trace_complete_fraction": record["trace_complete_fraction"],
        "ledger": {
            "feat_hits": stats.get("serve.feat_hits", 0),
            "feat_delta": stats.get("serve.feat_delta", 0),
            "feat_misses": stats.get("serve.feat_misses", 0),
            "cache_hits": stats.get("sched.cache_hits", 0),
            "inflight_dedup": stats.get("sched.inflight_dedup", 0),
        },
    })
    workload_rec.close()
    if workload_rec.path:
        record["workload_log"] = workload_rec.path
        record["workload_events"] = workload_rec.events_recorded
    if metrics_server is not None:
        metrics_server.stop()
    if owns_tracer:
        tracer.close()
    if emit:
        _emit(record)
    return record


# ------------------------------------------------------------- serve-scan ---


def _serve_scan_sizes() -> dict:
    """The variant-scan flagship: one parent sequence plus its full
    single-point deep-mutational-scan (19 substitutions x parent_len
    positions ~= 20*L variants, every mutant distinct so the result cache
    never short-circuits featurization accounting). CPU-mesh sized like
    the other serve flagships; AF2TPU_SERVE_SCAN_* knobs rescale it and
    mark the record non-flagship (never baseline-compared)."""
    parent_len = _env_int("AF2TPU_SERVE_SCAN_PARENT_LEN", 24)
    full_scan = parent_len * 19  # every (position, substitution) once
    return {
        "parent_len": parent_len,
        "variants": _env_int("AF2TPU_SERVE_SCAN_VARIANTS", full_scan),
        "max_batch": _env_int("AF2TPU_SERVE_SCAN_MAX_BATCH", 8),
        # cold arm: this many variants dispatched one at a time through an
        # identical engine with the fast lane off — the denominator of the
        # amortized-speedup claim, same machine, same compile
        "cold_sample": _env_int("AF2TPU_SERVE_SCAN_COLD_SAMPLE", 16),
        "dim": _env_int("AF2TPU_SERVE_SCAN_DIM", 32),
        "depth": _env_int("AF2TPU_SERVE_SCAN_DEPTH", 1),
        "heads": _env_int("AF2TPU_SERVE_SCAN_HEADS", 2),
        "dim_head": _env_int("AF2TPU_SERVE_SCAN_DIM_HEAD", 16),
        "msa_depth": _env_int("AF2TPU_SERVE_SCAN_MSA_DEPTH", 2),
        "mds_iters": _env_int("AF2TPU_SERVE_SCAN_MDS_ITERS", 20),
        "dwell_ms": float(os.environ.get("AF2TPU_SERVE_SCAN_DWELL_MS", 10.0)),
        "seed": _env_int("AF2TPU_SERVE_SCAN_SEED", 0),
    }


def scan_config_overridden() -> bool:
    return any(k.startswith("AF2TPU_SERVE_SCAN_") for k in os.environ)


def _serve_scan_metric(s: dict) -> str:
    return (
        f"serve-scan variants/sec parent_len={s['parent_len']} "
        f"variants={s['variants']} max_batch={s['max_batch']} "
        f"cold_sample={s['cold_sample']} dim={s['dim']} depth={s['depth']} "
        f"msa_depth={s['msa_depth']} mds_iters={s['mds_iters']} "
        f"dwell_ms={s['dwell_ms']:g}"
    )


def _scan_mutants(parent: str, n: int, rng) -> list:
    """``n`` DISTINCT single-point mutants of ``parent`` in a seeded
    shuffled order — a deep mutational scan submits position-sweeps, but
    shuffling makes the affinity former's job honest (siblings are found
    by family, not by accidental adjacency)."""
    alpha = "ACDEFGHIKLMNPQRSTVWY"
    all_muts = [
        parent[:i] + aa + parent[i + 1:]
        for i in range(len(parent))
        for aa in alpha
        if aa != parent[i]
    ]
    rng.shuffle(all_muts)
    return all_muts[:n]


def bench_serve_scan(emit: bool = True, tracer: Tracer | None = None) -> dict:
    """Variant-scan fast-lane bench: amortized per-variant latency of a
    deep mutational scan through the scan lane vs the cold path.

    Two arms on the same machine in one process:

    - **scan lane** — parent + ``variants`` distinct point mutants (one
      seed: delta featurization requires seed equality) submitted as a
      burst to an ``AsyncServeFrontend`` with the content-addressed
      FeatureCache, delta featurization and parent-affinity batching on.
      Amortized per-variant latency = wall / requests.
    - **cold path** — ``cold_sample`` of the same variants dispatched ONE
      AT A TIME through an identical engine with the fast lane disabled:
      each pays featurization, batch padding and a whole dispatch alone,
      which is exactly what today's cache-miss mutant traffic pays.

    The record's ``speedup_vs_cold`` (cold per-variant / scan per-variant)
    is the tentpole's >=5x acceptance bar, gated absolutely in
    observe/regress.py SERVE_SCAN_THRESHOLDS. The featurization-reuse
    ledger must fully account the scan arm: ``feat_hits + feat_misses +
    feat_delta == requests`` (every dispatched request bumps exactly one),
    recorded as ``ledger_accounted_frac``. The record carries
    ``"scan": true`` — a comparability variant key, so scan records never
    ratio against plain serve records."""
    import numpy as np

    from alphafold2_tpu.config import (
        Config, DataConfig, ModelConfig, ServeConfig,
    )
    from alphafold2_tpu.observe import Histogram
    from alphafold2_tpu.serve import (
        AsyncServeFrontend, ServeEngine, ServeRequest,
    )

    owns_tracer = tracer is None
    tracer = tracer if tracer is not None else _tracer()
    s = _serve_scan_sizes()
    bucket = s["parent_len"]  # one rung: a scan is single-length traffic
    rng = np.random.default_rng(s["seed"])
    alpha = "ACDEFGHIKLMNPQRSTVWY"
    parent = "".join(rng.choice(list(alpha), size=s["parent_len"]))
    mutants = _scan_mutants(parent, s["variants"], rng)
    n_requests = 1 + len(mutants)  # parent + mutants

    def _cfg(fast_lane: bool) -> Config:
        return Config(
            model=ModelConfig(
                dim=s["dim"], depth=s["depth"], heads=s["heads"],
                dim_head=s["dim_head"], max_seq_len=3 * bucket,
                bfloat16=jax.devices()[0].platform != "cpu",
            ),
            data=DataConfig(msa_depth=s["msa_depth"]),
            serve=ServeConfig(
                buckets=(bucket,), max_batch=s["max_batch"],
                mds_iters=s["mds_iters"], dwell_ms=s["dwell_ms"],
                # the whole scan queues as one burst: deep queue, no
                # shedding (0 disables the watermark), no deadline —
                # admission control is not what this bench measures
                queue_depth=n_requests + 64,
                shed_watermark=0.0,
                default_deadline_s=0.0,
                feature_cache_size=(n_requests + 16) if fast_lane else 0,
                delta_featurize=fast_lane,
                affinity_batching=fast_lane,
            ),
        )

    with _bench_stage(tracer, "serve_scan:backend_init"):
        engine = ServeEngine(_cfg(fast_lane=True), tracer=tracer)
    with _bench_stage(tracer, "serve_scan:trace_compile"):
        t0 = time.perf_counter()
        engine.warmup()  # compiles only; featurizes nothing (clean ledger)
        compile_s = time.perf_counter() - t0

    # ---- scan-lane arm: the whole scan as one burst ----
    frontend = AsyncServeFrontend(engine, tracer=tracer)
    with _bench_stage(tracer, "serve_scan:timed_scan"):
        t0 = time.perf_counter()
        handles = [frontend.submit(ServeRequest(parent, seed=s["seed"]))]
        handles += [
            frontend.submit(ServeRequest(
                m, seed=s["seed"], parent_id="scan-parent-0"
            ))
            for m in mutants
        ]
        results = [h.result(timeout=600) for h in handles]
        scan_wall = time.perf_counter() - t0
    frontend.close()
    stats = engine.counters.snapshot()
    ok = [r for r in results if r.status == "ok"]
    lat = Histogram()
    for r in ok:
        lat.observe(r.latency_s)
    lat_ms = lat.snapshot(unit_scale=1e3, digits=4) if ok else {"count": 0}

    # featurization-reuse ledger: every dispatched request bumped exactly
    # one of the three counters, and every result carries its entry
    feat_hits = stats.get("serve.feat_hits", 0)
    feat_misses = stats.get("serve.feat_misses", 0)
    feat_delta = stats.get("serve.feat_delta", 0)
    featurized = feat_hits + feat_misses + feat_delta
    by_reuse: dict = {}
    for r in results:
        by_reuse[r.feat_reuse] = by_reuse.get(r.feat_reuse, 0) + 1
    ledger = {
        "feat_hits": feat_hits,
        "feat_misses": feat_misses,
        "feat_delta": feat_delta,
        "featurized": featurized,
        "requests": n_requests,
        "results_by_reuse": {str(k): v for k, v in by_reuse.items()},
    }

    # ---- cold arm: one variant per dispatch, fast lane off ----
    with _bench_stage(tracer, "serve_scan:cold_arm"):
        cold_engine = ServeEngine(
            _cfg(fast_lane=False), params=engine.params, tracer=tracer
        )
        cold_engine.warmup()
        sample = mutants[: max(1, s["cold_sample"])]
        t0 = time.perf_counter()
        for m in sample:
            cold_engine.predict_many([ServeRequest(m, seed=s["seed"])])
        cold_wall = time.perf_counter() - t0
        cold_engine.close()
    _PHASE["name"] = "serve_scan:record"

    scan_per_variant = scan_wall / max(1, len(ok))
    cold_per_variant = cold_wall / len(sample)
    speedup = (
        cold_per_variant / scan_per_variant if scan_per_variant > 0 else 0.0
    )
    fc_stats = (
        engine.feature_cache.stats()
        if engine.feature_cache is not None else {}
    )
    engine.close()
    hists = {
        (n[:-2] + "_ms" if n.endswith("_s") else n): snap
        for n, snap in {
            **engine.histogram_snapshots(unit_scale=1e3),
            **frontend.histogram_snapshots(unit_scale=1e3),
        }.items()
    }
    hists["latency_e2e_ms"] = lat_ms
    # flat padding-fraction scalars beside the nested histograms: the
    # obs_report variant-scan section reads metrics.jsonl, which only
    # carries scalars
    pad_flat = {}
    for hname, key in (("affinity_pad_fraction", "affinity_pad_p50"),
                       ("regular_pad_fraction", "regular_pad_p50")):
        snap = hists.get(hname) or {}
        if snap.get("count"):
            pad_flat[key] = round(snap.get("p50", 0.0), 4)

    record = {
        "metric": _serve_scan_metric(s),
        "value": round(len(ok) / scan_wall, 1) if scan_wall > 0 else 0.0,
        "unit": "variants/sec",
        "mode": "serve-scan",
        # comparability variant key: scan records only ever ratio against
        # scan records (observe/regress.py comparable_reason)
        "scan": True,
        "speedup_vs_cold": round(speedup, 2),
        "scan_ms_per_variant": round(scan_per_variant * 1e3, 2),
        "cold_ms_per_variant": round(cold_per_variant * 1e3, 2),
        "cold_sampled": len(sample),
        "reuse_ledger": ledger,
        "ledger_accounted_frac": (
            round(featurized / n_requests, 4) if n_requests else 0.0
        ),
        "reuse_fraction": (
            round((feat_hits + feat_delta) / featurized, 4)
            if featurized else 0.0
        ),
        "feature_cache": fc_stats,
        "p50_ms": round(lat_ms.get("p50", 0.0), 1),
        "p95_ms": round(lat_ms.get("p95", 0.0), 1),
        "requests": n_requests,
        "completed": len(ok),
        "affinity_batches": stats.get("sched.affinity_batches", 0),
        "family_members": stats.get("sched.family_members", 0),
        "family_inflight_joins": stats.get(
            "sched.family_inflight_joins", 0
        ),
        "inflight_admitted": stats.get("sched.inflight_admitted", 0),
        "dispatches": stats.get("sched.dispatches", 0),
        "compiles": stats.get("serve.compiles", 0),
        "compile_s": round(compile_s, 1),
        "histograms": hists,
        **pad_flat,
        "device": jax.devices()[0].device_kind,
        "pipeline": engine.pipeline_desc,
    }
    if _CLOCK["probe"] is not None:
        record["clock_probe"] = _CLOCK["probe"]
        if not _CLOCK["probe"]["ok"]:
            record["clock_suspect"] = True

    baseline_path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "bench_serve_scan_baseline.json",
    )
    vs, compared = 1.0, False
    if (
        os.path.exists(baseline_path)
        and not scan_config_overridden()
        and not record.get("clock_suspect")
    ):
        with open(baseline_path) as f:
            base = json.load(f)
        if (
            base.get("value")
            and base.get("metric") == record["metric"]
            and base.get("device") == record["device"]
            and base.get("pipeline") == record.get("pipeline")
            and bool(base.get("scan")) == bool(record.get("scan"))
        ):
            vs = record["value"] / base["value"]
            compared = True
    record["vs_baseline"] = round(vs, 3)
    record["vs_baseline_valid"] = compared and not record.get("clock_suspect")
    if record.get("clock_suspect"):
        record["vs_baseline"] = 0.0

    if (
        os.environ.get("AF2TPU_SERVE_RECORD_BASELINE") == "1"
        and not scan_config_overridden()
        and not record.get("clock_suspect")
    ):
        with open(baseline_path, "w") as f:
            json.dump(record, f, indent=2)
        print(
            f"recorded serve-scan baseline -> {baseline_path}",
            file=sys.stderr,
        )

    logger = _metrics_logger()
    if logger is not None:
        logger.log(0, stats)
        logger.log(0, {
            k: v for k, v in record.items()
            if isinstance(v, (int, float, str, bool))
        })
    if owns_tracer:
        tracer.close()
    if emit:
        _emit(record)
    return record


# ----------------------------------------------------------- serve-replay ---


def _serve_replay_sizes() -> dict:
    """The record→replay flagship: a seeded synthetic diurnal stream
    through the full fast-lane frontend, recorded and replayed in one
    process. AF2TPU_SERVE_REPLAY_* knobs rescale it (CI smoke) and mark
    the record non-flagship."""
    return {
        "requests": _env_int("AF2TPU_SERVE_REPLAY_REQUESTS", 40),
        "mean_rate": float(os.environ.get("AF2TPU_SERVE_REPLAY_RATE", 8.0)),
        "period_s": float(
            os.environ.get("AF2TPU_SERVE_REPLAY_PERIOD_S", 4.0)
        ),
        "amplitude": float(
            os.environ.get("AF2TPU_SERVE_REPLAY_AMPLITUDE", 0.8)
        ),
        "buckets": tuple(
            int(x) for x in os.environ.get(
                "AF2TPU_SERVE_REPLAY_BUCKETS", "12,16"
            ).split(",")
        ),
        "max_batch": _env_int("AF2TPU_SERVE_REPLAY_MAX_BATCH", 4),
        "dim": _env_int("AF2TPU_SERVE_REPLAY_DIM", 32),
        "depth": _env_int("AF2TPU_SERVE_REPLAY_DEPTH", 1),
        "heads": _env_int("AF2TPU_SERVE_REPLAY_HEADS", 2),
        "dim_head": _env_int("AF2TPU_SERVE_REPLAY_DIM_HEAD", 16),
        "msa_depth": _env_int("AF2TPU_SERVE_REPLAY_MSA_DEPTH", 2),
        "mds_iters": _env_int("AF2TPU_SERVE_REPLAY_MDS_ITERS", 20),
        "dwell_ms": float(
            os.environ.get("AF2TPU_SERVE_REPLAY_DWELL_MS", 10.0)
        ),
        "deadline_s": float(
            os.environ.get("AF2TPU_SERVE_REPLAY_DEADLINE_S", 60.0)
        ),
        "seed": _env_int("AF2TPU_SERVE_REPLAY_SEED", 0),
    }


def _replay_args(argv=None) -> dict:
    """The replay driver's knobs, bench_mode-style: ``--time-warp`` /
    ``--load-scale`` / ``--replay-log`` (``--flag value`` or
    ``--flag=value``), with AF2TPU_SERVE_REPLAY_{WARP,SCALE,LOG} env
    fallbacks."""
    args = sys.argv[1:] if argv is None else argv

    def flag(name: str, env: str, default: str) -> str:
        for i, a in enumerate(args):
            if a == name and i + 1 < len(args):
                return args[i + 1]
            if a.startswith(name + "="):
                return a.split("=", 1)[1]
        return os.environ.get(env, default)

    return {
        "time_warp": float(
            flag("--time-warp", "AF2TPU_SERVE_REPLAY_WARP", "1.0")
        ),
        "load_scale": int(
            flag("--load-scale", "AF2TPU_SERVE_REPLAY_SCALE", "1")
        ),
        "log": flag("--replay-log", "AF2TPU_SERVE_REPLAY_LOG", "") or None,
    }


def replay_config_overridden(ra: dict | None = None) -> bool:
    """Any env resize, an external log, or non-default warp/scale marks
    the record non-flagship: never baseline-compared, never re-recorded."""
    if any(k.startswith("AF2TPU_SERVE_REPLAY_") for k in os.environ):
        return True
    if ra is None:
        return False
    return bool(
        ra["log"] or ra["time_warp"] != 1.0 or ra["load_scale"] != 1
    )


def _serve_replay_metric(s: dict, ra: dict) -> str:
    source = "log" if ra["log"] else "synthetic-diurnal"
    return (
        f"serve-replay residues/sec source={source} "
        f"requests={s['requests']} rate={s['mean_rate']:g}/s "
        f"period_s={s['period_s']:g} amp={s['amplitude']:g} "
        f"warp={ra['time_warp']:g} scale={ra['load_scale']} "
        f"buckets={','.join(map(str, s['buckets']))} "
        f"max_batch={s['max_batch']} dim={s['dim']} depth={s['depth']} "
        f"msa_depth={s['msa_depth']} mds_iters={s['mds_iters']} "
        f"dwell_ms={s['dwell_ms']:g}"
    )


def _drive_stream(frontend, pairs) -> tuple:
    """Open-loop submission of a timed (offset, request) stream: each
    request goes in at its offset from stream start whether or not earlier
    ones resolved. Returns (results, wall_s) aligned with ``pairs``."""
    t0 = time.perf_counter()
    handles = []
    for off, req in pairs:
        delay = t0 + off - time.perf_counter()
        if delay > 0:
            time.sleep(delay)
        handles.append(frontend.submit(req))
    results = [h.result(timeout=600) for h in handles]
    return results, time.perf_counter() - t0


def _recorder_overhead_probe(engine, s: dict, arms: int = 2,
                             n_requests: int = 12) -> dict:
    """The workload recorder's cost, measured exactly like
    ``_telemetry_overhead_probe``: identical closed-loop bursts through
    fresh frontends on the ALREADY-WARM engine, alternating recorder off
    and on (both hooks + a real JSONL append per event), best-of-arms."""
    import tempfile

    import numpy as np

    from alphafold2_tpu.observe.workload import WorkloadRecorder
    from alphafold2_tpu.serve import AsyncServeFrontend, ServeRequest

    rng = np.random.default_rng(s["seed"] + 1)
    lo = max(4, s["buckets"][0] // 2)
    alpha = "ACDEFGHIKLMNPQRSTVWY"
    n = max(1, n_requests)
    seqs = [
        "".join(rng.choice(
            list(alpha), size=int(rng.integers(lo, s["buckets"][-1] + 1))
        ))
        for _ in range(n)
    ]

    def run(recording: bool) -> float:
        fe = AsyncServeFrontend(engine)
        rec = None
        path = None
        if recording:
            fd, path = tempfile.mkstemp(suffix=".jsonl",
                                        prefix="af2tpu_wkld_probe_")
            os.close(fd)
            rec = WorkloadRecorder(
                path=path, record_raw=True,
                buckets=s["buckets"], msa_depth=s["msa_depth"],
            )
            fe.add_submit_observer(rec.on_submit)
            fe.add_observer(rec.observe)
        try:
            t0 = time.perf_counter()
            handles = [
                fe.submit(ServeRequest(seq=q, seed=j, priority=1))
                for j, q in enumerate(seqs)
            ]
            n_ok = sum(
                1 for h in handles if h.result(timeout=600).status == "ok"
            )
            wall = time.perf_counter() - t0
            fe.close()
            return n_ok / wall if wall > 0 else 0.0
        finally:
            if rec is not None:
                rec.close()
            if path is not None:
                try:
                    os.unlink(path)
                except OSError:
                    pass

    best = {"off": 0.0, "on": 0.0}
    for _ in range(max(1, arms)):
        for name, on in (("off", False), ("on", True)):
            best[name] = max(best[name], run(on))
    frac = (
        max(0.0, 1.0 - best["on"] / best["off"]) if best["off"] else 0.0
    )
    return {
        "goodput_rps_off": round(best["off"], 3),
        "goodput_rps_on": round(best["on"], 3),
        "requests_per_arm": n,
        "arms": arms,
        "overhead_frac": round(frac, 4),
    }


def bench_serve_replay(emit: bool = True,
                       tracer: Tracer | None = None) -> dict:
    """Workload record→replay bench: the deterministic replay driver and
    the loop's own gate, in one process.

    - **record arm** (skipped when ``--replay-log`` points at an existing
      recording): a seeded synthetic diurnal stream
      (:func:`observe.workload.synthetic_diurnal` — inhomogeneous Poisson
      arrivals riding a sinusoidal load curve, with duplicate and
      single-point-mutant traffic) runs open-loop through a fast-lane
      ``AsyncServeFrontend`` with a raw-opt-in :class:`WorkloadRecorder`
      attached, producing a replayable JSONL recording plus its closing
      summary (the reuse ledger, goodput, latency tails).
    - **replay arm**: the recording is loaded and re-issued with original
      timing against a FRESH engine (fresh feature cache, fresh counters)
      — ``--time-warp`` divides every arrival offset, ``--load-scale``
      multiplies each request into distinct-seed copies. The record
      carries the replay-vs-record diff: ``ledger_match`` (the replay
      reproduced the recording's feature-reuse ledger EXACTLY),
      ``replay_bytes_identical`` (same (seq, seed) → byte-identical
      atom14 outputs across arms), goodput/latency ratios, the replay
      arm's trace completeness, and ``recorder_overhead_frac`` measured
      on/off on the warm engine — all gated by REPLAY_THRESHOLDS
      (observe/regress.py). Non-default warp/scale/log marks the record
      non-flagship (its own ``replay`` comparability key)."""
    import hashlib
    import tempfile

    from alphafold2_tpu.config import (
        Config, DataConfig, ModelConfig, ServeConfig,
    )
    from alphafold2_tpu.observe import Histogram
    from alphafold2_tpu.observe.tracectx import trace_completeness
    from alphafold2_tpu.observe.workload import (
        WorkloadRecorder, build_replay, load_workload, replayable_reason,
        synthetic_diurnal,
    )
    from alphafold2_tpu.serve import AsyncServeFrontend, ServeEngine

    owns_tracer = tracer is None
    tracer = tracer if tracer is not None else _tracer()
    if not tracer.enabled:
        # trace completeness over the replay arm needs live events even
        # when no trace file was requested
        tracer = Tracer(enabled=True)
        owns_tracer = True
    s = _serve_replay_sizes()
    ra = _replay_args()
    n_expected = s["requests"]

    def _cfg() -> Config:
        return Config(
            model=ModelConfig(
                dim=s["dim"], depth=s["depth"], heads=s["heads"],
                dim_head=s["dim_head"], max_seq_len=3 * s["buckets"][-1],
                bfloat16=jax.devices()[0].platform != "cpu",
            ),
            data=DataConfig(msa_depth=s["msa_depth"]),
            serve=ServeConfig(
                buckets=s["buckets"], max_batch=s["max_batch"],
                mds_iters=s["mds_iters"], dwell_ms=s["dwell_ms"],
                # replay determinism needs admission control out of the
                # way: deep queue, no shedding, per-request deadlines only
                queue_depth=max(256, 4 * n_expected * ra["load_scale"]),
                shed_watermark=0.0,
                default_deadline_s=s["deadline_s"],
                feature_cache_size=4 * n_expected * ra["load_scale"] + 16,
                delta_featurize=True,
                affinity_batching=True,
            ),
        )

    with _bench_stage(tracer, "serve_replay:backend_init"):
        engine = ServeEngine(_cfg(), tracer=tracer)
    with _bench_stage(tracer, "serve_replay:trace_compile"):
        t0 = time.perf_counter()
        engine.warmup()
        compile_s = time.perf_counter() - t0

    # ---- record arm (or load an external recording) ----
    ref_hashes: dict = {}
    if ra["log"]:
        log_path = ra["log"]
        source = "log"
    else:
        fd, log_path = tempfile.mkstemp(suffix=".jsonl",
                                        prefix="af2tpu_workload_")
        os.close(fd)
        source = "synthetic-diurnal"
        stream = synthetic_diurnal(
            seed=s["seed"], requests=s["requests"],
            mean_rate=s["mean_rate"], period_s=s["period_s"],
            amplitude=s["amplitude"], buckets=s["buckets"],
            msa_depth=s["msa_depth"], deadline_s=s["deadline_s"],
        )
        recorder = WorkloadRecorder(
            path=log_path, record_raw=True,  # synthetic: raw is safe
            buckets=s["buckets"], msa_depth=s["msa_depth"],
        )
        fe = AsyncServeFrontend(engine, tracer=tracer)
        fe.add_submit_observer(recorder.on_submit)
        fe.add_observer(recorder.observe)
        with _bench_stage(tracer, "serve_replay:timed_record"):
            rec_pairs = build_replay(stream)  # original timing, 1x
            rec_results, rec_wall = _drive_stream(fe, rec_pairs)
        fe.close()
        rec_stats = engine.counters.snapshot()
        rec_ok = [r for r in rec_results if r.status == "ok"]
        rec_lat = Histogram()
        for r in rec_ok:
            rec_lat.observe(r.latency_s)
        rec_snap = (
            rec_lat.snapshot(unit_scale=1e3, digits=4)
            if rec_ok else {"count": 0}
        )
        rec_completeness = trace_completeness(
            tracer.events(),
            [r.trace_id for r in rec_results
             if r.status != "rejected" and r.trace_id],
        )
        recorder.write_summary({
            "requests": len(rec_results),
            "completed": len(rec_ok),
            "goodput_rps": round(len(rec_ok) / rec_wall, 3),
            "p50_ms": round(rec_snap.get("p50", 0.0), 1),
            "p95_ms": round(rec_snap.get("p95", 0.0), 1),
            "trace_complete_fraction": rec_completeness["fraction"],
            "ledger": {
                "feat_hits": rec_stats.get("serve.feat_hits", 0),
                "feat_delta": rec_stats.get("serve.feat_delta", 0),
                "feat_misses": rec_stats.get("serve.feat_misses", 0),
            },
        })
        recorder.close()
        # the byte-determinism reference: (seq, seed) -> atom14 digest
        for (_, req), r in zip(rec_pairs, rec_results):
            if r.status == "ok":
                ref_hashes[(req.seq, req.seed)] = hashlib.sha256(
                    r.atom14.tobytes()
                ).hexdigest()

    recording = load_workload(log_path)
    submits, ref_summary = recording["submits"], recording["summary"]
    reason = replayable_reason(submits)
    if reason is not None:
        raise RuntimeError(f"recording not replayable: {reason}")

    # ---- replay arm: fresh engine(s), fresh caches, fresh counters.
    # AF2TPU_SERVE_REPLAY_FLEET=N replays through an N-replica
    # FleetFrontend instead of a single cell — the per-cell contract
    # (byte determinism per (seq, seed), trace completeness across the
    # hop) must survive fleet routing; the reuse ledger is summed across
    # cells but its EXACT reproduction is only claimable single-cell
    # (load-balanced placement legitimately re-splits the feature
    # caches), so ledger_match stays a 1-replica gate ----
    fleet_n = max(1, _env_int("AF2TPU_SERVE_REPLAY_FLEET", 1))
    with _bench_stage(tracer, "serve_replay:replay_init"):
        replay_engines = [
            ServeEngine(_cfg(), params=engine.params, tracer=tracer)
            for _ in range(fleet_n)
        ]
        replay_engine = replay_engines[0]
        for eng in replay_engines:
            eng.warmup()
    if fleet_n > 1:
        from alphafold2_tpu.serve import FleetFrontend

        frontend = FleetFrontend(replay_engines, tracer=tracer)
    else:
        frontend = AsyncServeFrontend(replay_engine, tracer=tracer)
    with _bench_stage(tracer, "serve_replay:timed_run"):
        pairs = build_replay(
            submits, time_warp=ra["time_warp"],
            load_scale=ra["load_scale"],
        )
        results, wall = _drive_stream(frontend, pairs)
    frontend.close()
    stats: dict = {}
    for eng in replay_engines:
        for k, v in eng.counters.snapshot().items():
            stats[k] = stats.get(k, 0) + v
    _PHASE["name"] = "serve_replay:record"

    ok = [r for r in results if r.status == "ok"]
    lat = Histogram()
    for r in ok:
        lat.observe(r.latency_s)
    lat_ms = lat.snapshot(unit_scale=1e3, digits=4) if ok else {"count": 0}
    completeness = trace_completeness(
        tracer.events(),
        [r.trace_id for r in results
         if r.status != "rejected" and r.trace_id],
    )
    replay_ledger = {
        "feat_hits": stats.get("serve.feat_hits", 0),
        "feat_delta": stats.get("serve.feat_delta", 0),
        "feat_misses": stats.get("serve.feat_misses", 0),
    }
    # compare on the featurize-reuse keys only: recording summaries may
    # carry extra ledger entries (serve-async adds cache_hits/dedup),
    # but exact replay is claimed over the deterministic feat_* classes
    ref_ledger = (ref_summary or {}).get("ledger")
    if ref_ledger is not None:
        ref_ledger = {k: ref_ledger.get(k, 0) for k in replay_ledger}

    # byte determinism: every replayed (seq, seed) the record arm also
    # completed must produce byte-identical atom14 (checked on a bounded
    # sample; only meaningful with in-process reference hashes)
    bytes_identical = None
    if ref_hashes:
        compared = matched = 0
        for (_, req), r in zip(pairs, results):
            if r.status != "ok" or compared >= 32:
                continue
            ref = ref_hashes.get((req.seq, req.seed))
            if ref is None:
                continue
            compared += 1
            if hashlib.sha256(r.atom14.tobytes()).hexdigest() == ref:
                matched += 1
        if compared:
            bytes_identical = 1.0 if matched == compared else round(
                matched / compared, 4
            )

    with _bench_stage(tracer, "serve_replay:overhead_probe"):
        overhead = _recorder_overhead_probe(replay_engine, s)

    hists = {
        (n[:-2] + "_ms" if n.endswith("_s") else n): snap
        for n, snap in {
            **replay_engine.histogram_snapshots(unit_scale=1e3),
            **frontend.histogram_snapshots(unit_scale=1e3),
        }.items()
    }
    hists["latency_e2e_ms"] = lat_ms

    record = {
        "metric": _serve_replay_metric(s, ra),
        "value": (
            round(sum(len(r.seq) for r in ok) / wall, 1)
            if wall > 0 else 0.0
        ),
        "unit": "residues/sec",
        "mode": "serve-replay",
        "source": source,
        "time_warp": ra["time_warp"],
        "load_scale": ra["load_scale"],
        "workload_log": log_path,
        "p50_ms": round(lat_ms.get("p50", 0.0), 1),
        "p95_ms": round(lat_ms.get("p95", 0.0), 1),
        "goodput_rps": round(len(ok) / wall, 3) if wall > 0 else 0.0,
        "requests": len(results),
        "completed": len(ok),
        "rejected": sum(1 for r in results if r.status == "rejected"),
        "deadline_misses": sum(
            1 for r in results if r.status == "deadline_exceeded"
        ),
        "reuse_ledger": {
            "replay": replay_ledger,
            **({"record": ref_ledger} if ref_ledger else {}),
        },
        "trace": completeness,
        "trace_complete_fraction": completeness["fraction"],
        "recorder_overhead": overhead,
        "recorder_overhead_frac": overhead["overhead_frac"],
        "histograms": hists,
        "dispatches": stats.get("sched.dispatches", 0),
        "compiles": engine.counters.snapshot().get("serve.compiles", 0),
        "compile_s": round(compile_s, 1),
        "device": jax.devices()[0].device_kind,
        "pipeline": replay_engine.pipeline_desc,
    }
    # comparability variant key, carried only when non-default (an
    # external log or warped/scaled stream measures a different offered
    # workload than the flagship synthetic roundtrip)
    if ra["log"] or ra["time_warp"] != 1.0 or ra["load_scale"] != 1:
        record["replay"] = (
            f"warp{ra['time_warp']:g}-scale{ra['load_scale']}"
            + ("-log" if ra["log"] else "")
        )
    if fleet_n > 1:
        # comparability variant key, like the serve-fleet records: an
        # N-cell replay measures a different serving topology
        record["replicas"] = fleet_n
    # the loop's structural gates: exact reuse-ledger reproduction is
    # only claimable at 1x load (scaled copies are new work by design)
    # through one cell (fleet placement re-splits the feature caches)
    if ref_ledger is not None and ra["load_scale"] == 1 and fleet_n == 1:
        record["ledger_match"] = (
            1.0 if replay_ledger == ref_ledger else 0.0
        )
    if bytes_identical is not None:
        record["replay_bytes_identical"] = bytes_identical
    if ref_summary:
        for k in ("goodput_rps", "p50_ms", "p95_ms"):
            if ref_summary.get(k):
                record[f"record_{k}"] = ref_summary[k]
        if ref_summary.get("goodput_rps") and record["goodput_rps"]:
            record["replay_vs_record_goodput"] = round(
                record["goodput_rps"] / ref_summary["goodput_rps"], 3
            )
    if _CLOCK["probe"] is not None:
        record["clock_probe"] = _CLOCK["probe"]
        if not _CLOCK["probe"]["ok"]:
            record["clock_suspect"] = True

    baseline_path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "bench_serve_replay_baseline.json",
    )
    vs, compared = 1.0, False
    if (
        os.path.exists(baseline_path)
        and not replay_config_overridden(ra)
        and not record.get("clock_suspect")
    ):
        with open(baseline_path) as f:
            base = json.load(f)
        if (
            base.get("value")
            and base.get("metric") == record["metric"]
            and base.get("device") == record["device"]
            and base.get("pipeline") == record.get("pipeline")
            and base.get("replay") == record.get("replay")
        ):
            vs = record["value"] / base["value"]
            compared = True
    record["vs_baseline"] = round(vs, 3)
    record["vs_baseline_valid"] = compared and not record.get("clock_suspect")
    if record.get("clock_suspect"):
        record["vs_baseline"] = 0.0

    if (
        os.environ.get("AF2TPU_SERVE_RECORD_BASELINE") == "1"
        and not replay_config_overridden(ra)
        and not record.get("clock_suspect")
    ):
        with open(baseline_path, "w") as f:
            json.dump(record, f, indent=2)
        print(
            f"recorded serve-replay baseline -> {baseline_path}",
            file=sys.stderr,
        )

    logger = _metrics_logger()
    if logger is not None:
        logger.log(0, stats)
        logger.log(0, {
            k: v for k, v in record.items()
            if isinstance(v, (int, float, str, bool))
        })
    engine.close()
    for eng in replay_engines:
        eng.close()
    if owns_tracer:
        tracer.close()
    if emit:
        _emit(record)
    return record


# ------------------------------------------------------------ serve-fleet ---


def _serve_fleet_sizes() -> dict:
    """The fleet-serving flagship: one open-loop offered stream through N
    replica cells behind the health-aware router, CPU-mesh sized like the
    other serve flagships. The arrival rate deliberately exceeds a single
    replica's capacity so the reference arm saturates and the N-replica
    goodput ratio measures real horizontal scaling, not idle slack.
    AF2TPU_SERVE_FLEET_* knobs rescale it — any of them set marks the
    record non-flagship (never baseline-compared)."""
    buckets = tuple(
        int(v) for v in os.environ.get(
            "AF2TPU_SERVE_FLEET_BUCKETS", "12,16"
        ).split(",") if v
    )
    return {
        "replicas": _env_int("AF2TPU_SERVE_FLEET_REPLICAS", 2),
        "buckets": buckets,
        "max_batch": _env_int("AF2TPU_SERVE_FLEET_MAX_BATCH", 2),
        "requests": _env_int("AF2TPU_SERVE_FLEET_REQUESTS", 48),
        "rate": float(os.environ.get("AF2TPU_SERVE_FLEET_RATE", 200.0)),
        "dup_fraction": 0.1,  # workload definition: repeat-sequence share
        "dim": _env_int("AF2TPU_SERVE_FLEET_DIM", 32),
        "depth": _env_int("AF2TPU_SERVE_FLEET_DEPTH", 1),
        "heads": _env_int("AF2TPU_SERVE_FLEET_HEADS", 2),
        "dim_head": _env_int("AF2TPU_SERVE_FLEET_DIM_HEAD", 16),
        "msa_depth": _env_int("AF2TPU_SERVE_FLEET_MSA_DEPTH", 2),
        "mds_iters": _env_int("AF2TPU_SERVE_FLEET_MDS_ITERS", 20),
        "dwell_ms": float(
            os.environ.get("AF2TPU_SERVE_FLEET_DWELL_MS", 10.0)
        ),
        # deep enough that the saturating backlog is queued, not shed:
        # admission rejections would pollute the goodput ratio
        "queue_depth": _env_int("AF2TPU_SERVE_FLEET_QUEUE_DEPTH", 96),
        "deadline_s": float(
            os.environ.get("AF2TPU_SERVE_FLEET_DEADLINE_S", 120.0)
        ),
        "seed": _env_int("AF2TPU_SERVE_FLEET_SEED", 0),
        # replica fault spec for the drill arm ("replica=1,at_s=2" kill /
        # "degrade=0.05" latency); empty = the built-in mid-run kill
        "fault": os.environ.get("AF2TPU_SERVE_FLEET_FAULT", ""),
    }


def fleet_config_overridden() -> bool:
    return any(k.startswith("AF2TPU_SERVE_FLEET_") for k in os.environ)


def _serve_fleet_metric(s: dict) -> str:
    return (
        f"serve-fleet residues/sec replicas={s['replicas']} "
        f"buckets={','.join(map(str, s['buckets']))} "
        f"max_batch={s['max_batch']} requests={s['requests']} "
        f"rate={s['rate']:g}/s dup={s['dup_fraction']:g} dim={s['dim']} "
        f"depth={s['depth']} msa_depth={s['msa_depth']} "
        f"mds_iters={s['mds_iters']} dwell_ms={s['dwell_ms']:g} "
        f"queue={s['queue_depth']}"
    )


def _drive_fleet_stream(frontend, pairs, timeout: float = 240.0) -> tuple:
    """Open-loop submission like :func:`_drive_stream`, but an unresolved
    handle is COUNTED instead of raising — the zero-silent-drops claim is
    the measurement, so a dropped request must surface as a number, not a
    bench crash. Returns (results-with-None-for-unresolved, wall_s,
    unresolved_count)."""
    t0 = time.perf_counter()
    handles = []
    for off, req in pairs:
        delay = t0 + off - time.perf_counter()
        if delay > 0:
            time.sleep(delay)
        handles.append(frontend.submit(req))
    results: list = []
    unresolved = 0
    for h in handles:
        try:
            results.append(h.result(timeout=timeout))
        except TimeoutError:
            unresolved += 1
            results.append(None)
    return results, time.perf_counter() - t0, unresolved


def bench_serve_fleet(emit: bool = True, tracer: Tracer | None = None) -> dict:
    """Multi-replica fleet bench: horizontal goodput scaling and the
    replica-death drill, in one record.

    Three arms over the SAME deterministic offered stream (seeded request
    list + Poisson arrival offsets, re-minted per arm so every arm owns
    fresh trace identities), all on engines sharing ONE parameter set:

    - **reference arm**: a 1-replica ``FleetFrontend`` — router overhead
      included, so the speedup ratio isolates horizontal scaling.
    - **fleet arm**: the N-replica fleet. ``fleet_speedup`` = fleet
      goodput / reference goodput, gated >= 1.6 at 2 replicas
      (FLEET_THRESHOLDS, observe/regress.py).
    - **drill arm**: the N-replica fleet with a mid-run replica kill
      (``AF2TPU_SERVE_FLEET_FAULT`` spec, or a built-in kill of the last
      replica at 40% of the fleet arm's wall). The claim is structural:
      every accepted request resolves to a terminal ServeResult
      (``accepted_unresolved`` == 0 — queued work on the dead replica
      re-routes to survivors, dispatched work completes), and trace
      reconstruction stays >= 99% ACROSS the router→replica traceparent
      hop, kill included.

    The record carries ``replicas`` always — it is a comparability
    variant key, so a 2-replica number never ratios a 4-replica
    baseline."""
    import numpy as np

    from alphafold2_tpu.config import (
        Config, DataConfig, ModelConfig, ServeConfig,
    )
    from alphafold2_tpu.observe import Histogram
    from alphafold2_tpu.observe.slo import (
        default_serve_slos, parse_slo_specs,
    )
    from alphafold2_tpu.observe.tracectx import trace_completeness
    from alphafold2_tpu.serve import FleetFaultPlan, ServeEngine, ServeRequest
    from alphafold2_tpu.serve.fleet import FleetFrontend, fleet_counter_zeros

    owns_tracer = tracer is None
    tracer = tracer if tracer is not None else _tracer()
    if not tracer.enabled:
        # the cross-hop trace-reconstruction gate needs live events even
        # when no trace file was requested
        tracer = Tracer(enabled=True)
        owns_tracer = True
    s = _serve_fleet_sizes()
    n_replicas = max(1, s["replicas"])

    with _bench_stage(tracer, "serve_fleet:backend_init"):
        cfg = Config(
            model=ModelConfig(
                dim=s["dim"], depth=s["depth"], heads=s["heads"],
                dim_head=s["dim_head"], max_seq_len=3 * s["buckets"][-1],
                bfloat16=jax.devices()[0].platform != "cpu",
            ),
            data=DataConfig(msa_depth=s["msa_depth"]),
            serve=ServeConfig(
                buckets=s["buckets"], max_batch=s["max_batch"],
                mds_iters=s["mds_iters"], dwell_ms=s["dwell_ms"],
                queue_depth=s["queue_depth"], shed_watermark=0.0,
                default_deadline_s=s["deadline_s"],
            ),
        )
        # one parameter set across the whole fleet: replica 0 initializes,
        # the rest alias (N replicas never re-initialize N times)
        engines = []
        for _ in range(n_replicas):
            engines.append(ServeEngine(
                cfg,
                params=engines[0].params if engines else None,
                tracer=tracer,
            ))
    with _bench_stage(tracer, "serve_fleet:trace_compile"):
        t0 = time.perf_counter()
        for eng in engines:
            eng.warmup()
        compile_s = time.perf_counter() - t0

    # the deterministic offered stream, shared by every arm: same (seq,
    # seed) list, same Poisson arrival offsets; each arm re-mints fresh
    # ServeRequest objects so its lifecycles own their trace identities
    rng = np.random.default_rng(s["seed"])
    lo = max(4, s["buckets"][0] // 2)
    alpha = "ACDEFGHIKLMNPQRSTVWY"
    spec: list = []  # [(seq, seed)]
    for i in range(s["requests"]):
        if spec and rng.random() < s["dup_fraction"]:
            spec.append(spec[int(rng.integers(0, len(spec)))])
        else:
            n = int(rng.integers(lo, s["buckets"][-1] + 1))
            spec.append((
                "".join(rng.choice(list(alpha), size=n)), i,
            ))
    offsets = np.cumsum(rng.exponential(1.0 / s["rate"], size=s["requests"]))

    def make_pairs() -> list:
        return [
            (float(off), ServeRequest(seq=q, seed=sd))
            for off, (q, sd) in zip(offsets, spec)
        ]

    slo_specs = parse_slo_specs(
        os.environ.get("AF2TPU_SLO_SPECS", "")
    ) or default_serve_slos(s["deadline_s"])

    # Prometheus exposition with every fleet counter zero-seeded from the
    # first scrape (the PR-13 absent-at-zero fix, fleet edition): the
    # collect closure reads whichever arm's fleet is live right now
    current: dict = {"fleet": None}
    metrics_server = exposition.serve_from_env(
        lambda: {
            **fleet_counter_zeros(n_replicas),
            **(
                current["fleet"].snapshot()
                if current["fleet"] is not None else {}
            ),
        }
    )

    def run_arm(arm_engines, fault=None, specs=None, stage="timed_run"):
        fleet = FleetFrontend(
            arm_engines, tracer=tracer, fault=fault, slo_specs=specs,
        )
        current["fleet"] = fleet
        try:
            with _bench_stage(tracer, f"serve_fleet:{stage}"):
                results, wall, unresolved = _drive_fleet_stream(
                    fleet, make_pairs()
                )
            snap = fleet.snapshot()
            slo = fleet.slo_summary()
        finally:
            fleet.close()
        resolved = [r for r in results if r is not None]
        ok = [r for r in resolved if r.status == "ok"]
        lat = Histogram()
        for r in ok:
            lat.observe(r.latency_s)
        lat_ms = lat.snapshot(unit_scale=1e3, digits=4) if ok else {"count": 0}
        completeness = trace_completeness(
            tracer.events(),
            [r.trace_id for r in resolved
             if r.status != "rejected" and r.trace_id],
        )
        return {
            "results": results,
            "ok": ok,
            "wall": wall,
            "unresolved": unresolved,
            "rejected": sum(
                1 for r in resolved if r.status == "rejected"
            ),
            "errors": sum(1 for r in resolved if r.status == "error"),
            "deadline_misses": sum(
                1 for r in resolved if r.status == "deadline_exceeded"
            ),
            "goodput_rps": round(len(ok) / wall, 3) if wall > 0 else 0.0,
            "residues_per_s": (
                round(sum(len(r.seq) for r in ok) / wall, 1)
                if wall > 0 else 0.0
            ),
            "lat_ms": lat_ms,
            "counters": snap,
            "slo": slo,
            "trace": completeness,
        }

    # reference arm: ONE replica behind the same router (overhead-equal)
    ref = run_arm(engines[:1], stage="timed_ref")
    # fleet arm: all N replicas, same offered stream
    fleet_arm = run_arm(
        engines, specs=slo_specs, stage="timed_fleet"
    )
    # drill arm: the same fleet with a mid-run replica kill. The built-in
    # default kills the LAST replica at 40% of the fleet arm's wall —
    # mid-backlog by construction, whatever this host's speed
    fault = FleetFaultPlan.from_spec(s["fault"]) or FleetFaultPlan(
        replica=n_replicas - 1,
        at_s=max(0.2, 0.4 * fleet_arm["wall"]),
    )
    drill = run_arm(engines, fault=fault, stage="timed_drill")
    _PHASE["name"] = "serve_fleet:record"

    speedup = (
        fleet_arm["goodput_rps"] / ref["goodput_rps"]
        if ref["goodput_rps"] else 0.0
    )
    # the cross-hop reconstruction claim covers the drill too: a kill must
    # not orphan lifecycles
    trace_fraction = min(
        fleet_arm["trace"]["fraction"], drill["trace"]["fraction"]
    )
    unresolved_total = (
        ref["unresolved"] + fleet_arm["unresolved"] + drill["unresolved"]
    )
    fleet_counters = fleet_arm["counters"]
    drill_counters = drill["counters"]

    record = {
        "metric": _serve_fleet_metric(s),
        "value": fleet_arm["residues_per_s"],
        "unit": "residues/sec",
        "mode": "serve-fleet",
        # ALWAYS carried: the comparability variant key fencing records
        # with different fleet widths from each other
        "replicas": n_replicas,
        "p50_ms": round(fleet_arm["lat_ms"].get("p50", 0.0), 1),
        "p95_ms": round(fleet_arm["lat_ms"].get("p95", 0.0), 1),
        "p99_ms": round(fleet_arm["lat_ms"].get("p99", 0.0), 1),
        "goodput_rps": fleet_arm["goodput_rps"],
        "ref_goodput_rps": ref["goodput_rps"],
        "fleet_speedup": round(speedup, 3),
        # replica dispatchers are OS threads: a single-core host cannot
        # express N-replica parallelism, so the regression gate applies
        # the fleet_speedup floor only where host_cpus >= 2
        "host_cpus": os.cpu_count() or 1,
        "requests": s["requests"],
        "completed": len(fleet_arm["ok"]),
        "rejected": fleet_arm["rejected"],
        "deadline_misses": fleet_arm["deadline_misses"],
        "dispatch_error_results": fleet_arm["errors"],
        # the structural gates: every accepted request reaches a terminal
        # result, in every arm, kill included
        "accepted_unresolved": drill["unresolved"],
        "dropped_requests": unresolved_total,
        "trace_complete_fraction": trace_fraction,
        "trace": {
            "fleet": fleet_arm["trace"],
            "drill": drill["trace"],
        },
        "fleet_counters": {
            k: v for k, v in sorted(fleet_counters.items())
            if k.startswith("fleet.")
        },
        "drill": {
            "fault": {
                "replica": fault.replica,
                "kind": fault.kind,
                "at_s": round(fault.at_s, 3),
                "fired": fault.fired,
            },
            "requests": s["requests"],
            "completed": len(drill["ok"]),
            "rejected": drill["rejected"],
            "unresolved": drill["unresolved"],
            "goodput_rps": drill["goodput_rps"],
            "rerouted": drill_counters.get("fleet.rerouted", 0),
            "steals": drill_counters.get("fleet.steals", 0),
            "drains": drill_counters.get("fleet.drains", 0),
            "replica_deaths": drill_counters.get(
                "fleet.replica_deaths", 0
            ),
        },
        "steals": fleet_counters.get("fleet.steals", 0),
        "rerouted": fleet_counters.get("fleet.rerouted", 0),
        "slo": fleet_arm["slo"],
        "compiles": sum(
            eng.counters.snapshot().get("serve.compiles", 0)
            for eng in engines
        ),
        "compile_s": round(compile_s, 1),
        "device": jax.devices()[0].device_kind,
        "pipeline": engines[0].pipeline_desc,
    }
    # per-replica goodput, flat beside the nested counters: the scrape
    # and obs_report's occupancy table address these by name
    for i in range(n_replicas):
        record[f"goodput_requests_replica{i}"] = fleet_counters.get(
            f"fleet.replica{i}.resolved_ok", 0
        )
    if _CLOCK["probe"] is not None:
        record["clock_probe"] = _CLOCK["probe"]
        if not _CLOCK["probe"]["ok"]:
            record["clock_suspect"] = True

    baseline_path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "bench_serve_fleet_baseline.json",
    )
    vs, compared = 1.0, False
    if (
        os.path.exists(baseline_path)
        and not fleet_config_overridden()
        and not record.get("clock_suspect")
    ):
        with open(baseline_path) as f:
            base = json.load(f)
        if (
            base.get("value")
            and base.get("metric") == record["metric"]
            and base.get("device") == record["device"]
            and base.get("pipeline") == record.get("pipeline")
            # different fleet widths are different measurements
            and base.get("replicas") == record.get("replicas")
        ):
            vs = record["value"] / base["value"]
            compared = True
    record["vs_baseline"] = round(vs, 3)
    record["vs_baseline_valid"] = compared and not record.get("clock_suspect")
    if record.get("clock_suspect"):
        record["vs_baseline"] = 0.0

    if (
        os.environ.get("AF2TPU_SERVE_RECORD_BASELINE") == "1"
        and not fleet_config_overridden()
        and not record.get("clock_suspect")
    ):
        with open(baseline_path, "w") as f:
            json.dump(record, f, indent=2)
        print(
            f"recorded serve-fleet baseline -> {baseline_path}",
            file=sys.stderr,
        )

    logger = _metrics_logger()
    if logger is not None:
        logger.log(0, record["fleet_counters"])
        logger.log(0, {
            k: v for k, v in record.items()
            if isinstance(v, (int, float, str, bool))
        })
    for eng in engines:
        closer = getattr(eng, "close", None)
        if closer is not None:
            try:
                closer()
            except Exception:
                pass
    if metrics_server is not None:
        metrics_server.stop()
    if owns_tracer:
        tracer.close()
    if emit:
        _emit(record)
    return record


# ---------------------------------------------------------------- kernels ---


def _kernels_sizes() -> dict:
    """The kernels-microbench flagship: three tied-row and three axial
    attention shapes, sized so the fused kernels' interpret-mode grids stay
    small on CPU hosts (the committed CPU baseline is an interpret-mode
    record; TPU sessions re-record compiled numbers under the same metric
    machinery, keyed by device). AF2TPU_KERNELS_BENCH_* overrides mark the
    record non-flagship (never baseline-compared)."""
    return {
        "iters": _env_int("AF2TPU_KERNELS_BENCH_ITERS", 5),
        # (B, H, N, D) — the axial per-device pass after row-flattening
        "axial": ((2, 4, 128, 64), (1, 4, 256, 64), (1, 2, 384, 64)),
        # (B, R, N, H, D) — tied-row MSA attention
        "tied": ((1, 4, 128, 4, 32), (1, 8, 128, 4, 64),
                 (2, 16, 64, 2, 32)),
    }


def kernels_config_overridden() -> bool:
    return any(k.startswith("AF2TPU_KERNELS_BENCH_") for k in os.environ)


def _kernels_metric(s: dict) -> str:
    fmt = lambda shapes: ",".join("x".join(map(str, sh)) for sh in shapes)
    return (
        f"kernels fused-vs-stock speedup axial={fmt(s['axial'])} "
        f"tied={fmt(s['tied'])} iters={s['iters']}"
    )


def bench_kernels(emit: bool = True, tracer: Tracer | None = None) -> dict:
    """Microbench: fused Pallas kernels vs stock XLA dense attention.

    Times the in-repo fused kernels (ops/pallas/axial.py, tied_row.py)
    against the jnp dense formulation at three shapes each, forward only
    (the serving hot path). On CPU the fused side runs in Pallas interpret
    mode — the committed CPU record is a regression canary for the
    interpret path and the dispatch plumbing, not a speed claim; on TPU the
    same driver times the compiled kernels and the speedup is the real
    number. One JSON line, device/kernel-keyed, gated by
    scripts/bench_compare.py against bench_kernels_baseline.json."""
    import numpy as np

    from alphafold2_tpu.ops.kernels import current_policy
    from alphafold2_tpu.ops.pallas.axial import fused_attention
    from alphafold2_tpu.ops.pallas.tied_row import tied_row_attention

    owns_tracer = tracer is None
    tracer = tracer if tracer is not None else _tracer()
    s = _kernels_sizes()
    iters = s["iters"]

    def dense_axial(q, k, v, mask, scale):
        dots = jnp.einsum("bhid,bhjd->bhij", q, k).astype(jnp.float32) * scale
        dots = jnp.where(mask[:, None, None, :], dots, -1e9)
        p = jax.nn.softmax(dots, axis=-1).astype(q.dtype)
        out = jnp.einsum("bhij,bhjd->bhid", p, v)
        return jnp.where(mask[:, None, :, None], out, 0)

    def dense_tied(q, k, v, mask, shared, scale, tie_scale):
        qz = jnp.where(mask[..., None, None], q, 0)
        kz = jnp.where(mask[..., None, None], k, 0)
        vz = jnp.where(mask[..., None, None], v, 0)
        dots = (
            jnp.einsum("brihd,brjhd->bhij", qz, kz).astype(jnp.float32)
            * scale * tie_scale
        )
        dots = jnp.where(shared[:, None, None, :], dots, -1e9)
        p = jax.nn.softmax(dots, axis=-1).astype(q.dtype)
        return jnp.einsum("bhij,brjhd->brihd", p, vz)

    def timed(fn, args):
        out = fn(*args)
        jax.block_until_ready(out)  # compile + warm outside the timing
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fn(*args)
        jax.block_until_ready(out)
        return (time.perf_counter() - t0) / iters * 1e3  # ms

    rng = np.random.default_rng(0)
    shapes: list = []
    with _bench_stage(tracer, "kernels:backend_init"):
        jax.devices()

    with _bench_stage(tracer, "kernels:timed_run"):
        for b, h, n, d in s["axial"]:
            q, k, v = (
                jnp.asarray(rng.standard_normal((b, h, n, d)), jnp.float32)
                for _ in range(3)
            )
            mask = jnp.ones((b, n), bool).at[:, -max(1, n // 10):].set(False)
            scale = d**-0.5
            fused = jax.jit(lambda q, k, v, m, sc=scale: fused_attention(
                q, k, v, q_mask=m, kv_mask=m, sm_scale=sc))
            stock = jax.jit(lambda q, k, v, m, sc=scale: dense_axial(
                q, k, v, m, sc))
            fused_ms = timed(fused, (q, k, v, mask))
            stock_ms = timed(stock, (q, k, v, mask))
            shapes.append({
                "name": f"axial_{b}x{h}x{n}x{d}",
                "fused_ms": round(fused_ms, 3),
                "stock_ms": round(stock_ms, 3),
                "speedup": round(stock_ms / max(fused_ms, 1e-9), 4),
            })
        for b, r, n, h, d in s["tied"]:
            q, k, v = (
                jnp.asarray(
                    rng.standard_normal((b, r, n, h, d)), jnp.float32
                )
                for _ in range(3)
            )
            mask = jnp.ones((b, r, n), bool).at[
                :, :, -max(1, n // 10):
            ].set(False)
            shared = mask.any(1)  # (B, N) shared column mask
            scale = d**-0.5
            tie = float(r) ** -0.5
            fused = jax.jit(
                lambda q, k, v, m, sm, sc=scale, t=tie: tied_row_attention(
                    jnp.where(m[..., None, None], q, 0),
                    jnp.where(m[..., None, None], k, 0),
                    jnp.where(m[..., None, None], v, 0),
                    q_mask=sm, kv_mask=sm, sm_scale=sc, tie_scale=t,
                )
            )
            stock = jax.jit(lambda q, k, v, m, sm, sc=scale, t=tie:
                            dense_tied(q, k, v, m, sm, sc, t))
            fused_ms = timed(fused, (q, k, v, mask, shared))
            stock_ms = timed(stock, (q, k, v, mask, shared))
            shapes.append({
                "name": f"tied_{b}x{r}x{n}x{h}x{d}",
                "fused_ms": round(fused_ms, 3),
                "stock_ms": round(stock_ms, 3),
                "speedup": round(stock_ms / max(fused_ms, 1e-9), 4),
            })
    _PHASE["name"] = "kernels:record"

    speedups = [sh["speedup"] for sh in shapes]
    geomean = float(np.exp(np.mean(np.log(np.maximum(speedups, 1e-9)))))
    interpret = jax.default_backend() != "tpu"
    record = {
        "metric": _kernels_metric(s),
        "value": round(geomean, 4),
        "unit": "x-speedup",
        "mode": "kernels",
        "fused_ms_total": round(sum(sh["fused_ms"] for sh in shapes), 3),
        "stock_ms_total": round(sum(sh["stock_ms"] for sh in shapes), 3),
        "shapes": shapes,
        # interpret-mode fused timings are a canary, not a speed claim —
        # the flag keeps that explicit in the committed record
        "interpret": interpret,
        "kernels": current_policy().describe(),
        "device": jax.devices()[0].device_kind,
    }

    baseline_path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "bench_kernels_baseline.json",
    )
    vs, compared = 1.0, False
    if os.path.exists(baseline_path) and not kernels_config_overridden():
        with open(baseline_path) as f:
            base = json.load(f)
        if (
            base.get("value")
            and base.get("metric") == record["metric"]
            and base.get("device") == record["device"]
            and base.get("kernels") == record.get("kernels")
        ):
            vs = record["value"] / base["value"]
            compared = True
    record["vs_baseline"] = round(vs, 3)
    record["vs_baseline_valid"] = compared

    if (
        os.environ.get("AF2TPU_KERNELS_RECORD_BASELINE") == "1"
        and not kernels_config_overridden()
    ):
        with open(baseline_path, "w") as f:
            json.dump(record, f, indent=2)
        print(
            f"recorded kernels baseline -> {baseline_path}", file=sys.stderr
        )

    logger = _metrics_logger()
    if logger is not None:
        logger.log(0, {
            k: v for k, v in record.items()
            if isinstance(v, (int, float, str, bool))
        })
    if owns_tracer:
        tracer.close()
    if emit:
        _emit(record)
    return record


def bench_mode(argv=None) -> str:
    """The bench mode: 'train' (default flagship step bench), 'serve'
    (closed-loop batched engine), 'serve-async' (open-loop frontend),
    'serve-scan' (variant-scan fast lane vs cold path), 'serve-replay'
    (workload record→replay roundtrip; also takes ``--time-warp``,
    ``--load-scale`` and ``--replay-log``; ``AF2TPU_SERVE_REPLAY_FLEET=N``
    replays against an N-replica fleet), 'serve-fleet' (N replica cells
    behind the health-aware router: scaling + replica-death drill) or
    'kernels' (fused-vs-stock attention microbench).
    Spelled ``--mode serve`` / ``--mode=serve-async`` or AF2TPU_BENCH_MODE."""
    args = sys.argv[1:] if argv is None else argv
    for i, a in enumerate(args):
        if a == "--mode" and i + 1 < len(args):
            return args[i + 1]
        if a.startswith("--mode="):
            return a.split("=", 1)[1]
    return os.environ.get("AF2TPU_BENCH_MODE", "train")


def _failure_record(msg: str) -> dict:
    """Diagnostic record: value 0.0 + an ``error`` field is unambiguous
    ("no measurement"), but stays parseable for the driver."""
    return {
        "metric": _metric(),
        "value": 0.0,
        "unit": "pairs/sec",
        "vs_baseline": 0.0,
        "vs_baseline_valid": False,
        "error": msg,
        "phase": _PHASE["name"],
    }


def _phase_failure_msg() -> str:
    """Deadline message that says WHICH phase died — 'backend init never
    returned' is a tunnel hang, 'trace_compile' is a too-slow/hung compile,
    'warmup/timed' is a run that is genuinely too slow for the budget."""
    phase = _PHASE["name"]
    if "backend_init" in phase:
        detail = "backend init never returned (tunnel hang)"
    elif "trace_compile" in phase:
        detail = "compile exceeded the remaining budget"
    elif "run" in phase:
        detail = "compiled run too slow for the remaining budget"
    else:
        detail = "died before touching the backend"
    return (
        f"deadline {DEADLINE}s exceeded during phase '{phase}': {detail}; "
        "raise AF2TPU_BENCH_DEADLINE for bigger configs"
    )


def _emit_failure(msg: str) -> None:
    """On flagship failure, prefer the completed first-light measurement
    (a real nonzero number at a smaller config) over a value-0.0 record."""
    rec = _FIRST_LIGHT["record"]
    if rec is not None:
        rec = dict(rec)
        rec["fallback"] = True
        rec["flagship_error"] = msg
        _emit(rec)
    else:
        _emit(_failure_record(msg))


import threading

_EMIT_LOCK = threading.Lock()
_emitted = False


def _emit(record: dict) -> None:
    """Write the one JSON result line. First writer wins: the watchdog and
    the main thread can race near the deadline, and the driver must never
    see two records."""
    global _emitted
    with _EMIT_LOCK:
        if _emitted:
            return
        _emitted = True
        sys.stdout.write(json.dumps(record) + "\n")
        sys.stdout.flush()


def _preflight_compile_mode() -> str:
    """Detect a dead remote-compile endpoint BEFORE this process commits
    (shared probe: alphafold2_tpu.preflight). Re-execs into client-side
    compile when that is the only working mode; otherwise returns the
    probe status. Budget: <=2 probes x 240 s against the 1500 s deadline."""
    from alphafold2_tpu.preflight import preflight_compile_mode

    return preflight_compile_mode(
        # evaluated right before a re-exec, AFTER the probes have burned
        # their share of the budget
        remaining_fn=(
            (lambda: max(1, int(DEADLINE - (time.monotonic() - _T0))))
            if DEADLINE > 0 else None
        ),
        deadline_env_var="AF2TPU_BENCH_DEADLINE",
    )


def _cold_cache_deadline_extension(preflight_status: str) -> int:
    """Extra watchdog seconds when the compile cache has no serialized
    executables AND the preflight just proved the tunnel alive.

    The 1500s default deadline assumes a tpu_session run pre-warmed the
    persistent cache; when the driver's bench is the round's first TPU
    touch, the flagship compile alone can exceed it — through a perfectly
    healthy tunnel. The deadline exists to catch *hangs*; after a
    successful liveness probe, a cold cache earns the known compile budget
    (AF2TPU_BENCH_COLD_EXTRA, default 600s) instead of a spurious kill."""
    if DEADLINE <= 0:
        return 0  # watchdog disabled: nothing to extend
    if preflight_status != "remote_ok" and not (
        preflight_status == "skipped"
        and os.environ.get("AF2TPU_PREFLIGHT_CLIENT_OK") == "1"
    ):
        return 0
    try:
        cache = alphafold2_tpu.compile_cache_dir()
        cold = not cache or not any(
            f for f in os.listdir(cache) if not f.startswith(".")
        )
    except (OSError, RuntimeError):  # unreadable/foreign-owned dir: the
        cold = True  # record machinery must survive (cache is optional)
    if not cold:
        return 0
    # the extension must keep the watchdog's ABSOLUTE fire time under the
    # EXTERNAL driver's kill (observed >= 30 min; AF2TPU_BENCH_DRIVER_BUDGET
    # documents the assumption) — a watchdog that outlives the driver emits
    # nothing and reintroduces the silent rc=124 loss it exists to prevent.
    # The driver's clock started at the FIRST interpreter of this process
    # chain (AF2TPU_BENCH_EPOCH0, set in __main__ before any preflight
    # re-exec), not at this process's _T0.
    driver_budget = _env_int("AF2TPU_BENCH_DRIVER_BUDGET", 2400)
    chain_elapsed = time.time() - float(
        os.environ.get("AF2TPU_BENCH_EPOCH0", time.time())
    )
    fire_in = DEADLINE - (time.monotonic() - _T0)  # watchdog, unextended
    extra = min(
        _env_int("AF2TPU_BENCH_COLD_EXTRA", 600),
        max(0, int(driver_budget - 60 - chain_elapsed - fire_in)),
    )
    if extra <= 0:
        return 0
    print(
        f"compile cache cold + tunnel probe healthy: extending bench "
        f"deadline by {extra}s for the first-run flagship compile",
        file=sys.stderr,
    )
    return extra


if __name__ == "__main__":
    import threading

    # wall-clock anchor of the WHOLE process chain: survives preflight
    # re-execs (setdefault keeps the first interpreter's value) so budget
    # math can account for time burned before a re-exec
    os.environ.setdefault("AF2TPU_BENCH_EPOCH0", str(time.time()))

    # crash flight recorder (observe/flightrec.py): opt-in via
    # AF2TPU_FLIGHTREC_DIR — rings of recent telemetry dumped as a
    # scrubbed incident file on watchdog fire / dispatch error / SIGTERM
    _flightrec_active = flightrec.maybe_install_from_env()
    if _flightrec_active is not None:
        flightrec.install_signal_handler(_flightrec_active)

    def _watchdog():
        # Backend init through the TPU tunnel can hang inside C++ with no
        # timeout; a daemon thread + os._exit is the only escape that still
        # gets a JSON line onto stdout before the driver's kill. Re-reads
        # the module-global DEADLINE each cycle: the cold-cache extension
        # below may raise it after this thread has started.
        while True:
            remaining = DEADLINE - (time.monotonic() - _T0)
            if remaining <= 0:
                break
            time.sleep(min(30.0, remaining))
        _emit_failure(_phase_failure_msg())
        os._exit(0)

    # watchdog FIRST: the preflight probes (2 x 240s subprocesses) must not
    # be able to outlive a short driver-set deadline with nothing on stdout
    if DEADLINE > 0:
        threading.Thread(target=_watchdog, daemon=True).start()

    # liveness watchdog (observe.LivenessWatchdog): a backend_init phase
    # overstaying INIT_DEADLINE triggers the cheap subprocess probe — dead
    # backend => structured `liveness: dead` failure record in well under a
    # minute (30s stage + 25s probe by default) instead of BENCH_r05's
    # silent 1500s burn; slow-but-alive => the stage earns another deadline
    def _on_liveness_dead(info: dict) -> None:
        rec_fr = flightrec.active()
        if rec_fr is not None:
            # the incident file first: _emit + os._exit lose the rings
            rec_fr.dump("liveness_dead", extra=dict(info))
        rec = _failure_record(
            f"backend liveness dead: phase '{info['stage']}' exceeded its "
            f"{info['stage_deadline_s']}s stage deadline and the backend "
            f"probe failed ({info['probe']})"
        )
        rec.update(info)
        _emit(rec)
        os._exit(0)

    _stage_deadlines = {}
    if INIT_DEADLINE > 0:
        _stage_deadlines["backend_init"] = INIT_DEADLINE
    if STAGE_DEADLINE > 0:
        # probe-and-bail past backend_init: compile and dispatch phases
        # get the same dead-tunnel detection (suffix matching covers the
        # serve:*/serve_async:*/first_light:* variants)
        for _st in ("trace_compile", "warmup_run", "clock_probe",
                    "timed_run"):
            _stage_deadlines[_st] = STAGE_DEADLINE
    if _stage_deadlines:
        LivenessWatchdog(
            stage_fn=lambda: _PHASE["name"],
            deadlines=_stage_deadlines,
            on_dead=_on_liveness_dead,
        ).start()

    _mode = bench_mode()
    if _mode in ("serve", "serve-async", "serve-scan", "serve-replay",
                 "serve-fleet", "kernels"):
        # the serve/kernels benches run wherever the engine runs (the CPU
        # mesh included — that is the point: valid perf numbers without the
        # tunnel); no preflight, no first-light, same watchdog + one-JSON-
        # line contract as the train bench
        try:
            {
                "serve": bench_serve,
                "serve-async": bench_serve_async,
                "serve-scan": bench_serve_scan,
                "serve-replay": bench_serve_replay,
                "serve-fleet": bench_serve_fleet,
                "kernels": bench_kernels,
            }[_mode]()
            sys.exit(0)
        except Exception as e:
            _emit_failure(f"{type(e).__name__}: {e}")
            raise

    preflight_status = _preflight_compile_mode()
    DEADLINE += _cold_cache_deadline_extension(preflight_status)

    # First light (VERDICT r3 #1a): measure a smaller config BEFORE the
    # flagship so a healthy-but-slow window still yields a nonzero record
    # — if the flagship compile then eats the rest of the budget, the
    # watchdog emits this result instead of a 0.0 failure. Skipped when the
    # operator already overrode the config (their override IS the config
    # under test) or the watchdog is disabled (nothing can eat the budget).
    if (
        os.environ.get("AF2TPU_BENCH_FIRST_LIGHT", "1") != "0"
        and not config_overridden()
        and DEADLINE > 0
    ):
        try:
            rec = main(
                overrides={"crop": 128, "msa_len": 128}, emit=False
            )
            _FIRST_LIGHT["record"] = rec
            print(
                f"first light: {rec['value']} pairs/sec at crop 128 "
                f"(mfu={rec.get('mfu')}); attempting flagship",
                file=sys.stderr,
            )
        except Exception as e:
            # a dead backend fails identically at the flagship attempt
            # below, which owns the retry/record logic
            print(f"first-light attempt failed ({type(e).__name__}: {e}); "
                  "proceeding to flagship", file=sys.stderr)

    # the tunneled-TPU backend can fail transiently at INIT; retry a few
    # times before giving up so a single flaky window doesn't lose the run.
    # Only init failures are retryable: once a backend initializes, jax
    # caches the client for the process lifetime, so a mid-run drop would
    # just reuse the dead client — those propagate immediately.
    attempts = max(1, _env_int("AF2TPU_BENCH_ATTEMPTS", 3))
    for i in range(attempts):
        try:
            main()
            break
        except RuntimeError as e:
            if "Unable to initialize backend" not in str(e):
                _emit_failure(f"{type(e).__name__}: {e}")
                raise
            remaining = (
                DEADLINE - (time.monotonic() - _T0)
                if DEADLINE > 0 else float("inf")
            )
            # a retry only helps if there is still time for the 60s backoff
            # plus a realistic init (~4-5 min through the tunnel)
            if i == attempts - 1 or remaining < 360:
                _emit_failure(
                    f"backend init failed ({i + 1} attempt(s), "
                    f"{remaining:.0f}s of {DEADLINE}s budget left): {e}"
                )
                sys.exit(0)
            print(f"backend init unavailable (attempt {i + 1}/{attempts}); "
                  "retrying in 60s", file=sys.stderr)
            time.sleep(60)
        except Exception as e:  # non-RuntimeError: still leave a record
            _emit_failure(f"{type(e).__name__}: {e}")
            raise
