#!/bin/bash
# Canonical test invocation: hermetic CPU jax with 8 virtual devices.
# PALLAS_AXON_POOL_IPS= disables the axon TPU relay hook in sitecustomize
# (it serializes every jax process through a single tunnel — tests must not
# touch it). See tests/conftest.py for the in-process fallback.
#
# Builds native/ first: without libaf2data.so the 14 C++-loader tests
# silently skip (VERDICT r2 weak #5), and a canonical run must not
# under-test. A missing toolchain fails LOUDLY; export AF2TPU_SKIP_NATIVE=1
# to opt out explicitly on toolchain-less hosts.
set -e
# resolve caller-relative test paths BEFORE cd'ing to the repo root, so
# `run_tests.sh ../foo/test_x.py` keeps working from any directory
ARGS=()
for a in "$@"; do
  if [[ "$a" != -* && -e "$a" ]]; then
    a="$(cd "$(dirname "$a")" && pwd)/$(basename "$a")"
  fi
  ARGS+=("$a")
done
cd "$(dirname "$0")"

# -O strips asserts: load-bearing checks on user-facing library paths must
# be raises, not asserts (VERDICT r3 #7). Allowed: tests/ (pytest idiom)
# and trace-time asserts inside Pallas kernel bodies (never run under -O'd
# user code — they execute at jit trace, and the kernels assert only on
# programmer-error block math).
if grep -rn --include='*.py' -E '^[[:space:]]*assert ' \
    alphafold2_tpu/ --exclude-dir=__pycache__ \
    | grep -v 'ops/pallas/' ; then
  echo "run_tests.sh: load-bearing 'assert' on a library path (use raise;" >&2
  echo "python -O strips asserts into silent wrong math). See above." >&2
  exit 1
fi

if [ "${AF2TPU_SKIP_NATIVE}" != "1" ]; then
  command -v "${CXX:-g++}" >/dev/null || {
    echo "run_tests.sh: ${CXX:-g++} not found — native/ cannot build, and" >&2
    echo "without libaf2data.so 14 loader tests silently skip. Install a" >&2
    echo "C++ toolchain (or export CXX) or set AF2TPU_SKIP_NATIVE=1 to" >&2
    echo "accept the skips." >&2
    exit 1
  }
  make -C native all >/dev/null
fi
exec env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
  XLA_FLAGS="--xla_force_host_platform_device_count=8" \
  python -m pytest "${ARGS[@]:-tests/}" -q
