#!/bin/bash
# Canonical test invocation: hermetic CPU jax with 8 virtual devices.
# PALLAS_AXON_POOL_IPS= disables the axon TPU relay hook in sitecustomize
# (it serializes every jax process through a single tunnel — tests must not
# touch it). See tests/conftest.py for the in-process fallback.
#
# Builds native/ first: without libaf2data.so the 14 C++-loader tests
# silently skip (VERDICT r2 weak #5), and a canonical run must not
# under-test. A missing toolchain fails LOUDLY; export AF2TPU_SKIP_NATIVE=1
# to opt out explicitly on toolchain-less hosts.
set -e
cd "$(dirname "$0")"
if [ "${AF2TPU_SKIP_NATIVE}" != "1" ]; then
  command -v "${CXX:-g++}" >/dev/null || {
    echo "run_tests.sh: ${CXX:-g++} not found — native/ cannot build, and" >&2
    echo "without libaf2data.so 14 loader tests silently skip. Install a" >&2
    echo "C++ toolchain (or export CXX) or set AF2TPU_SKIP_NATIVE=1 to" >&2
    echo "accept the skips." >&2
    exit 1
  }
  make -C native all >/dev/null
fi
exec env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
  XLA_FLAGS="--xla_force_host_platform_device_count=8" \
  python -m pytest "${@:-tests/}" -q
