#!/bin/bash
# Canonical test invocation: hermetic CPU jax with 8 virtual devices.
# PALLAS_AXON_POOL_IPS= disables the axon TPU relay hook in sitecustomize
# (it serializes every jax process through a single tunnel — tests must not
# touch it). See tests/conftest.py for the in-process fallback.
exec env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
  XLA_FLAGS="--xla_force_host_platform_device_count=8" \
  python -m pytest "${@:-tests/}" -q
