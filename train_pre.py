#!/usr/bin/env python
"""Distogram pretraining driver — CLI equivalent of reference train_pre.py,
with a real config system instead of in-source constants (SURVEY.md S5.6).

Usage:
  python train_pre.py                               # reference defaults
  python train_pre.py model.depth=12 data.crop_len=256 mesh.data_parallel=4
"""

import sys

import alphafold2_tpu
from alphafold2_tpu.config import Config, ModelConfig, parse_cli


def main(argv):
    alphafold2_tpu.setup_platform()  # AF2TPU_PLATFORM=cpu to force host
    from alphafold2_tpu.parallel.distributed import initialize

    initialize()  # multi-host process group (no-op single-process)
    base = Config(model=ModelConfig(dim=256, depth=1))  # train_pre.py:52-57
    cfg = parse_cli(argv, base)
    print("config:", cfg.to_json())
    from alphafold2_tpu.train.loop import train

    train(cfg)


if __name__ == "__main__":
    main(sys.argv[1:])
