#!/usr/bin/env python
"""Summarize observability artifacts: trace-event JSONL + metrics.jsonl.

    python scripts/obs_report.py trace.json [metrics.jsonl ...]

For each Chrome-trace-event file (written by ``observe.Tracer``, or any
trace the viewer loads): per-span totals (count, total/mean/max duration)
and percentile tables over span durations. For each metrics.jsonl
(``observe.MetricsLogger``): the latest counter values with compile /
cache-hit accounting (hit rate, compile seconds by shape) and HBM peaks.

Pure host-side: imports no jax, initializes no backend — it must run on a
laptop against artifacts scp'd from a TPU host (the reason MetricsLogger
grew its ``enabled=`` override). Exits 0 on success, 1 on no input files,
2 on unreadable input OR any truncated/malformed line (every parseable
record is still reported; the malformed lines get a structured per-file
summary on stderr instead of a mid-parse traceback — a killed writer's
half-flushed tail must not hide the rest of the artifact).

``--env`` echoes the AF2TPU_/JAX_/XLA_/TPU_ environment through the
flight recorder's scrub (secret-shaped values redacted, AXON_ dropped),
so a report pasted into a ticket carries the config without credentials.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from alphafold2_tpu.observe.flightrec import scrub_env
from alphafold2_tpu.observe.histogram import Histogram
from alphafold2_tpu.observe.tracectx import (
    RESOLVE_EVENT,
    SUBMIT_EVENT,
    reconstruct_traces,
    trace_incomplete_reason,
)
from alphafold2_tpu.observe.tracing import (
    DEVICE_SPAN_NAMES,
    device_idle_fraction,
    load_trace_events_lenient,
    merge_intervals,
)


def _fmt_s(seconds: float) -> str:
    if seconds >= 1.0:
        return f"{seconds:.3f}s"
    return f"{seconds * 1e3:.2f}ms"


def classify(path: str) -> str:
    """"trace" (Chrome trace events) vs "metrics" (MetricsLogger JSONL) vs
    "hlo-contracts" (analysis/hlo_audit.py snapshot) vs
    "concurrency-contracts" (analysis/concurrency.py baseline): trace files
    open with ``[`` or hold events with a ``ph`` key; metrics lines are
    flat records with a ``step`` key; an hlo_contracts.json is a single
    pretty-printed object with ``format`` + ``targets``; a
    concurrency_contracts.json has ``format`` + ``lock_graph``."""
    with open(path) as f:
        head = f.read(4096).lstrip()
    if head.startswith("["):
        return "trace"
    if head.startswith("{"):
        try:
            with open(path) as f:
                doc = json.load(f)
        except json.JSONDecodeError:
            doc = None
        if (isinstance(doc, dict) and "format" in doc
                and isinstance(doc.get("targets"), dict)):
            return "hlo-contracts"
        if (isinstance(doc, dict) and "format" in doc
                and isinstance(doc.get("lock_graph"), dict)):
            return "concurrency-contracts"
        if isinstance(doc, dict):
            # a single-record metrics file (e.g. one bench JSON line
            # longer than the sniff window) parses whole even when its
            # first 4096 bytes don't
            return "trace" if "ph" in doc else "metrics"
    first = head.splitlines()[0] if head else "{}"
    try:
        rec = json.loads(first)
    except json.JSONDecodeError:
        return "trace"
    return "trace" if "ph" in rec else "metrics"


def report_trace(path: str) -> list:
    """Span table + request-trace timelines. Returns the list of malformed-
    line descriptions (empty = clean file) for main()'s error summary."""
    events, errors = load_trace_events_lenient(path)
    spans = [e for e in events if e.get("ph") == "X"]
    print(f"== trace {path}: {len(events)} events, {len(spans)} spans ==")
    if not spans:
        report_fleet_timeline(events)
        report_request_traces(events)
        return errors
    by_name: dict = {}
    for e in spans:
        by_name.setdefault(e["name"], []).append(e.get("dur", 0.0) / 1e6)

    total_wall = sum(sum(v) for v in by_name.values())
    print(f"{'span':<28} {'count':>6} {'total':>10} {'mean':>10} "
          f"{'p50':>10} {'p95':>10} {'p99':>10} {'max':>10}")
    for name in sorted(by_name, key=lambda n: -sum(by_name[n])):
        durs = by_name[name]
        h = Histogram()
        for d in durs:
            h.observe(d)
        snap = h.snapshot()
        print(
            f"{name:<28} {len(durs):>6} {_fmt_s(sum(durs)):>10} "
            f"{_fmt_s(sum(durs) / len(durs)):>10} "
            f"{_fmt_s(snap['p50']):>10} {_fmt_s(snap['p95']):>10} "
            f"{_fmt_s(snap['p99']):>10} {_fmt_s(max(durs)):>10}"
        )
    print(f"{'(span-seconds, nested spans double-count)':<28} "
          f"{'':>6} {_fmt_s(total_wall):>10}")

    compiles = [e for e in spans if e["name"].endswith("compile")]
    if compiles:
        print("-- compiles --")
        for e in compiles:
            args = e.get("args", {})
            shape = ", ".join(f"{k}={v}" for k, v in sorted(args.items()))
            print(f"  {e['name']}({shape}): {_fmt_s(e.get('dur', 0) / 1e6)}")
    report_pipeline(events)
    report_fleet_timeline(events)
    report_request_traces(events)
    return errors


_HOST_SPAN_NAMES = ("serve.featurize", "serve.device_put")


def report_pipeline(events: list, max_shown: int = 12) -> None:
    """Pipelined-dispatch section (serve/pipeline.py): per-dispatch
    host/device timeline keyed by the ``dispatch_index`` span arg, the
    device-idle fraction over the dispatch window (the same
    ``device_idle_frac`` bench records gate), each device phase's overlap
    with OTHER dispatches' host work (the wall time double buffering
    actually reclaimed), and the in-flight admission count
    (``sched.inflight_admit`` instants from continuous batching)."""
    per: dict = {}
    for e in events:
        if e.get("ph") != "X":
            continue
        name = e.get("name")
        if name not in _HOST_SPAN_NAMES and name not in DEVICE_SPAN_NAMES:
            continue
        args = e.get("args") or {}
        if args.get("dispatch_index") is None:
            continue
        d = per.setdefault(
            args["dispatch_index"],
            {"host": [], "device": [], "bucket": args.get("bucket")},
        )
        iv = (e.get("ts", 0) / 1e6, (e.get("ts", 0) + e.get("dur", 0)) / 1e6)
        d["host" if name in _HOST_SPAN_NAMES else "device"].append(iv)
    per = {k: v for k, v in per.items() if v["device"]}
    if not per:
        return

    idle = device_idle_fraction(events)
    head = (f"device_idle_frac {idle['device_idle_frac']:.3f} over "
            f"{_fmt_s(idle['window_s'])}") if idle else "no device window"
    admits = sum(
        1 for e in events if e.get("name") == "sched.inflight_admit"
    )
    pipelined = sum(
        1 for e in events
        if e.get("name") == "serve.batch"
        and (e.get("args") or {}).get("pipelined")
    )
    print(f"-- pipelined dispatch ({len(per)} dispatches, {head}) --")
    t0 = min(iv[0] for d in per.values() for iv in d["host"] + d["device"])
    shown = 0
    for idx in sorted(per):
        if shown >= max_shown:
            print(f"  ... {len(per) - max_shown} more dispatches")
            break
        shown += 1
        d = per[idx]
        host = merge_intervals(d["host"])
        dev = merge_intervals(d["device"])
        # device time of THIS dispatch that ran while ANOTHER dispatch's
        # host stage was featurizing/transferring: the overlap the
        # pipeline reclaimed vs a serial host->device->host loop
        others = merge_intervals([
            iv for j, o in per.items() if j != idx for iv in o["host"]
        ])
        overlap = 0.0
        for ds, de in dev:
            for hs, he in others:
                overlap += max(0.0, min(de, he) - max(ds, hs))
        line = f"  #{idx:<4} bucket {str(d['bucket'] or '?'):>5} "
        if host:
            line += (f" host {_fmt_s(sum(e - s for s, e in host)):>9}"
                     f"@+{host[0][0] - t0:7.3f}s")
        else:
            line += f" host {'-':>9} {'':>9}"
        line += (f"  device {_fmt_s(sum(e - s for s, e in dev)):>9}"
                 f"@+{dev[0][0] - t0:7.3f}s")
        if overlap:
            line += f"  overlapped {_fmt_s(overlap)}"
        print(line)
    tail = f"  in-flight admissions: {admits}"
    if pipelined:
        tail += f"  (pipelined batches: {pipelined})"
    print(tail)


def report_request_traces(events: list, max_shown: int = 8) -> None:
    """Per-request lifecycle timelines reconstructed by trace_id: every
    request whose sched.submit root rode this file, its event sequence in
    ts order, its terminal status, and the completeness verdict (the same
    trace_incomplete_reason the CI gate's trace_complete_fraction uses),
    so a broken lifecycle names its missing link instead of just lowering
    a fraction."""
    traces = reconstruct_traces(events)
    # request traces only: the trace must own a sched.submit root (shared
    # batch spans list member trace_ids but belong to no single request)
    roots = {
        tid: evs for tid, evs in traces.items()
        if any(
            e.get("name") == SUBMIT_EVENT
            and (e.get("args") or {}).get("trace_id") == tid
            for e in evs
        )
    }
    if not roots:
        return
    reasons = {
        tid: trace_incomplete_reason(tid, evs) for tid, evs in roots.items()
    }
    n_ok = sum(1 for r in reasons.values() if r is None)
    print(f"-- request traces ({n_ok}/{len(roots)} complete) --")
    for i, tid in enumerate(sorted(roots)):
        if i >= max_shown:
            print(f"  ... {len(roots) - max_shown} more")
            break
        evs = sorted(roots[tid], key=lambda e: e.get("ts", 0))
        steps = []
        for e in evs:
            name = e.get("name", "?")
            if e.get("ph") == "X" and e.get("dur"):
                steps.append(f"{name}({_fmt_s(e['dur'] / 1e6)})")
            else:
                steps.append(name)
        status = next(
            ((e.get("args") or {}).get("status") for e in reversed(evs)
             if e.get("name") == RESOLVE_EVENT),
            "?",
        )
        verdict = "complete" if reasons[tid] is None else reasons[tid]
        print(f"  {tid[:12]} [{status}] {' > '.join(steps)}")
        if reasons[tid] is not None:
            print(f"    INCOMPLETE: {verdict}")


def _fin(values):
    """Finite subset (NaN-skipping min/max/mean must not be poisoned by the
    very anomalies the report exists to surface)."""
    return [v for v in values if isinstance(v, (int, float)) and v == v]


def report_train(records: list) -> None:
    """Training-run section of a metrics.jsonl: loss/grad-norm trajectory,
    skipped-step accounting, throughput, numerics anomalies and NaN-triage
    reports (train/loop.py's numerics telemetry)."""
    steps = [r for r in records if "loss" in r]
    if not steps:
        return
    print(f"-- train ({len(steps)} step records, steps "
          f"{steps[0].get('step')}..{steps[-1].get('step')}) --")
    losses = _fin([r["loss"] for r in steps])
    if losses:
        print(f"  loss:      {losses[0]:.4g} -> {losses[-1]:.4g}  "
              f"(min {min(losses):.4g})")
    gnorms = _fin([r.get("grad_norm") for r in steps])
    if gnorms:
        print(f"  grad_norm: first {gnorms[0]:.4g}  last {gnorms[-1]:.4g}  "
              f"min {min(gnorms):.4g}  max {max(gnorms):.4g}")
    groups = sorted({
        k.split("/", 1)[1] for r in steps for k in r
        if k.startswith("grad_norm/")
    })
    if groups:
        print(f"  per-group norms: {', '.join(groups)}")
    skipped = max((r.get("skipped", 0) for r in steps), default=0)
    not_ok = sum(1 for r in steps if r.get("grads_ok") in (0, 0.0, False))
    print(f"  skipped steps: {int(skipped)} total "
          f"({not_ok} of the logged steps had non-finite grads)")
    first = next((r["first_step_s"] for r in steps if "first_step_s" in r),
                 None)
    if first is not None:
        print(f"  first step: {_fmt_s(first)}")
    compile_s = next(
        (r["compile_s"] for r in records if "compile_s" in r), None
    )
    if compile_s is not None:
        print(f"  step compile: {_fmt_s(compile_s)}")
    rates = _fin([r.get("steps_per_sec") for r in steps])
    if rates:
        tail = f"  (mfu {steps[-1]['mfu']:.2%})" if "mfu" in steps[-1] else ""
        print(f"  steps/sec: last {rates[-1]:.4g}  max {max(rates):.4g}"
              + tail)

    # numerics anomalies: any logged tensor stat with NaN/Inf entries
    anomalies = sorted({
        k[len("numerics/"):k.rfind("/")]
        for r in records
        for k, v in r.items()
        if k.startswith("numerics/")
        and (k.endswith("/nan_count") or k.endswith("/inf_count"))
        and isinstance(v, (int, float)) and v > 0
    })
    if anomalies:
        print(f"  numerics anomalies (tensors with NaN/Inf): "
              f"{', '.join(anomalies)}")
    triages = [r for r in records if r.get("event") == "nan_triage"]
    for t in triages:
        print(f"  nan_triage @ step {t.get('step')}: first non-finite = "
              f"{t.get('first_nonfinite')} "
              f"({len(t.get('nonfinite', []))} tensors non-finite)")


def report_scheduler(latest: dict) -> None:
    """Async-frontend section of a metrics.jsonl: admission-control and
    queue outcomes from the ``sched.*`` counters the scheduler shares with
    the engine (serve/scheduler.py), plus the open-loop latency/queue
    summary when a serve-async bench record rode the same file. The
    queue-depth / time-to-dispatch / dwell distributions live in the
    bench record's ``histograms``; the trace file's ``sched.dispatch`` /
    ``sched.retry`` spans appear in the standard span table."""
    if not any(k.startswith("sched.") for k in latest):
        return
    submitted = latest.get("sched.submitted", 0)
    rejected = latest.get("sched.rejected", 0)
    print(f"-- async scheduler ({int(submitted)} submitted) --")
    print(f"  admitted:       {int(latest.get('sched.admitted', 0))}")
    shed = latest.get("sched.shed", 0)
    rate = rejected / submitted if submitted else 0.0
    print(f"  rejected:       {int(rejected)}  ({rate:.1%}; "
          f"{int(shed)} load-shed past the watermark)")
    print(f"  deadline miss:  {int(latest.get('sched.deadline_miss', 0))}")
    hits = latest.get("sched.cache_hits", 0)
    dedup = latest.get("sched.inflight_dedup", 0)
    saved = (hits + dedup) / submitted if submitted else 0.0
    print(f"  result cache:   {int(hits)} hits + {int(dedup)} in-flight "
          f"dedups ({saved:.1%} of submissions never dispatched)")
    retries = latest.get("sched.retries", 0)
    errors = latest.get("serve.dispatch_errors", 0)
    if retries or errors:
        print(f"  faults:         {int(errors)} dispatch errors, "
              f"{int(retries)} requests retried on another executable")
    dispatches = latest.get("sched.dispatches", 0)
    batched = latest.get("sched.batched_requests", 0)
    if dispatches:
        print(f"  dispatches:     {int(dispatches)}  "
              f"(mean batch {batched / dispatches:.2f} requests)")
    for key, label in (("p50_ms", "p50"), ("p95_ms", "p95"),
                       ("p99_ms", "p99")):
        if key not in latest:
            break
    else:
        print(f"  e2e latency:    p50 {latest['p50_ms']:.1f}ms  "
              f"p95 {latest['p95_ms']:.1f}ms  p99 {latest['p99_ms']:.1f}ms")


def report_variant_scan(latest: dict) -> None:
    """Variant-scan fast-lane section: printed when the featurization
    ledger counters (``serve.feat_*``) or a ``--mode serve-scan`` bench
    record rode the file. Shows the featurize-reuse ratio (hit/delta/miss
    accounting), mutant-family sizes from the affinity former, and the
    padding fraction of affinity-formed vs regular batch formations."""
    hits = latest.get("serve.feat_hits", 0)
    misses = latest.get("serve.feat_misses", 0)
    delta = latest.get("serve.feat_delta", 0)
    featurized = hits + misses + delta
    is_scan = latest.get("mode") == "serve-scan" or latest.get("scan")
    if not featurized and not is_scan:
        return
    print("-- variant scan --")
    if featurized:
        reuse = (hits + delta) / featurized
        print(f"  featurize reuse: {reuse:.1%} of {int(featurized)} "
              f"featurized requests "
              f"({int(hits)} cache hits + {int(delta)} delta-patched "
              f"mutants; {int(misses)} cold)")
    members = latest.get("sched.family_members", 0)
    batches = latest.get("sched.affinity_batches", 0)
    joins = latest.get("sched.family_inflight_joins", 0)
    if members:
        size = f"  (mean {members / batches:.1f} per batch)" if batches \
            else ""
        print(f"  families:        {int(members)} family members over "
              f"{int(batches)} affinity-formed batches{size}")
    if joins:
        print(f"  late siblings:   {int(joins)} joined their family's "
              f"in-flight batch")
    aff = latest.get("affinity_pad_p50")
    reg = latest.get("regular_pad_p50")
    if aff is not None or reg is not None:
        parts = []
        if aff is not None:
            parts.append(f"affinity-formed p50 {aff:.1%}")
        if reg is not None:
            parts.append(f"regular p50 {reg:.1%}")
        print(f"  padding:         {'  vs  '.join(parts)}")
    if latest.get("speedup_vs_cold") is not None:
        print(f"  amortized:       {latest['speedup_vs_cold']}x vs the "
              f"cold path "
              f"({latest.get('scan_ms_per_variant')}ms/variant scanned, "
              f"{latest.get('cold_ms_per_variant')}ms/variant cold)")
    if latest.get("ledger_accounted_frac") is not None:
        frac = latest["ledger_accounted_frac"]
        ok = "fully accounted" if frac >= 1.0 else "UNACCOUNTED"
        print(f"  ledger:          {frac:.1%} of requests accounted "
              f"({ok})")


def report_replay(latest: dict) -> None:
    """Record-vs-replay section: printed when a ``--mode serve-replay``
    bench record rode the file. Shows the recording source and replay
    knobs, the replay-vs-record goodput/latency diff, the structural
    verdicts the CI gate judges absolutely (exact reuse-ledger
    reproduction, byte-identical (seq, seed) outputs, trace completeness)
    and the measured recorder overhead."""
    if latest.get("mode") != "serve-replay":
        return
    knobs = (f"warp {latest.get('time_warp', 1)}x, "
             f"scale {latest.get('load_scale', 1)}x")
    print(f"-- record vs replay ({latest.get('source', '?')}, {knobs}) --")
    goodput = latest.get("goodput_rps")
    rec_goodput = latest.get("record_goodput_rps")
    if goodput is not None:
        line = f"  replay goodput:  {goodput} req/s"
        if rec_goodput:
            ratio = latest.get("replay_vs_record_goodput")
            line += f"  (recorded {rec_goodput} req/s"
            if ratio is not None:
                line += f", {ratio}x"
            line += ")"
        print(line)
    if latest.get("p50_ms") is not None:
        line = (f"  replay latency:  p50 {latest['p50_ms']}ms  "
                f"p95 {latest.get('p95_ms')}ms")
        if latest.get("record_p50_ms") is not None:
            line += (f"  (recorded p50 {latest['record_p50_ms']}ms  "
                     f"p95 {latest.get('record_p95_ms')}ms)")
        print(line)
    match = latest.get("ledger_match")
    if match is not None:
        verdict = "EXACT" if match >= 1.0 else "MISMATCH"
        print(f"  reuse ledger:    {verdict} reproduction of the "
              f"recording's hit/delta/miss ledger")
    bytes_id = latest.get("replay_bytes_identical")
    if bytes_id is not None:
        verdict = ("byte-identical" if bytes_id >= 1.0
                   else f"DIVERGED ({bytes_id:.1%} matched)")
        print(f"  (seq, seed):     {verdict} atom14 outputs across arms")
    frac = latest.get("trace_complete_fraction")
    if frac is not None:
        print(f"  replay traces:   {frac:.1%} complete")
    overhead = latest.get("recorder_overhead_frac")
    if overhead is not None:
        print(f"  recorder cost:   {overhead:.1%} goodput overhead "
              f"(on/off on the warm engine)")
    if latest.get("workload_log"):
        print(f"  recording:       {latest['workload_log']}")


def report_fleet(latest: dict) -> None:
    """Fleet-serving section: printed when a ``--mode serve-fleet`` bench
    record (or a metrics file carrying ``fleet.*`` counters) rode the
    file. Shows the per-replica goodput/occupancy table, the steal /
    drain / reroute accounting, the death-drill outcome (the zero-drop
    contract the CI gate judges absolutely) and the cross-replica trace
    verdict — one trace per request spanning the router hop."""
    counters = latest.get("fleet_counters") or {
        k: v for k, v in latest.items() if k.startswith("fleet.")
    }
    if latest.get("mode") != "serve-fleet" and not counters:
        return
    n = int(latest.get("replicas") or 0)
    speed = latest.get("fleet_speedup")
    head = f"{n} replica(s)" if n else "counters only"
    if speed is not None:
        head += (f", {speed}x goodput vs the 1-replica reference "
                 f"({latest.get('goodput_rps')} vs "
                 f"{latest.get('ref_goodput_rps')} req/s)")
    print(f"-- fleet serving ({head}) --")
    if n:
        print(f"  {'replica':<9} {'routed':>8} {'resolved ok':>12} "
              f"{'goodput req':>12}")
        for i in range(n):
            routed = counters.get(f"fleet.replica{i}.routed", 0)
            ok = counters.get(f"fleet.replica{i}.resolved_ok", 0)
            good = latest.get(f"goodput_requests_replica{i}", ok)
            print(f"  {i:<9} {int(routed):>8} {int(ok):>12} "
                  f"{int(good):>12}")
    moved = counters.get("fleet.steals", 0)
    rerouted = counters.get("fleet.rerouted", 0)
    drains = counters.get("fleet.drains", 0)
    print(f"  rebalancing:    {int(moved)} stolen, {int(rerouted)} "
          f"rerouted, {int(drains)} drain(s), "
          f"{int(counters.get('fleet.no_replica', 0))} with no live "
          f"replica")
    drill = latest.get("drill") or {}
    if drill:
        fault = drill.get("fault") or {}
        fired = "fired" if fault.get("fired") else "NOT FIRED"
        unresolved = drill.get("unresolved", 0)
        verdict = ("ZERO DROPPED" if not unresolved
                   else f"{int(unresolved)} UNRESOLVED")
        print(f"  death drill:    {fault.get('kind', '?')} replica "
              f"{fault.get('replica', '?')} at {fault.get('at_s', '?')}s "
              f"({fired}): {drill.get('completed', 0)}/"
              f"{drill.get('requests', 0)} completed, "
              f"{int(drill.get('rerouted', 0))} rerouted -> {verdict}")
    frac = latest.get("trace_complete_fraction")
    if frac is not None:
        print(f"  hop traces:     {frac:.1%} reconstruct end-to-end "
              f"across the router->replica hop")


_FLEET_EVENT_NAMES = ("fleet.steal", "fleet.drain", "fleet.degrade",
                      "fleet.reroute")


def report_fleet_timeline(events: list, max_shown: int = 20) -> None:
    """Steal/drain timeline from the router's instant events: what the
    health pump did and when, relative to the first fleet admission."""
    acts = [e for e in events if e.get("name") in _FLEET_EVENT_NAMES]
    if not acts:
        return
    admits = [e.get("ts", 0) for e in events
              if e.get("name") == "fleet.admit"]
    t0 = min(admits) if admits else min(e.get("ts", 0) for e in acts)
    reroutes = sum(1 for e in acts if e.get("name") == "fleet.reroute")
    print(f"-- fleet timeline ({len(acts)} router action(s), "
          f"{reroutes} reroute(s)) --")
    shown = 0
    for e in sorted(acts, key=lambda e: e.get("ts", 0)):
        if e.get("name") == "fleet.reroute":
            continue  # per-request noise; counted in the header
        if shown >= max_shown:
            print("  ...")
            break
        shown += 1
        args = e.get("args") or {}
        at = (e.get("ts", 0) - t0) / 1e6
        if e["name"] == "fleet.steal":
            detail = (f"moved {args.get('n')} request(s) replica "
                      f"{args.get('from_replica')} -> "
                      f"{args.get('to_replica')}")
        elif e["name"] == "fleet.drain":
            detail = (f"replica {args.get('replica')} drained "
                      f"({args.get('reason', '?')})")
        else:
            detail = (f"replica {args.get('replica')} degraded "
                      f"+{args.get('delay_s')}s/dispatch")
        print(f"  +{at:8.3f}s  {e['name']:<13} {detail}")


def report_kernels(latest: dict) -> None:
    """Kernels/precision section: printed when records carry the kernel-
    policy or serving-dtype keys (ops/kernels.py KernelPolicy, serve.dtype)
    or a --mode kernels microbench record rode the file. Shows the resolved
    policy, the serving dtype and the per-kernel FLOPs attribution
    (observe.flops: tied-row vs axial vs rest) so MFU conversations can
    name the kernel responsible."""
    compile_records = latest.get("compile_records") or []
    by_kernel = latest.get("flops_by_kernel") or {}
    has_keys = (
        latest.get("kernels") or latest.get("dtype")
        or latest.get("mode") == "kernels" or by_kernel
        or any(c.get("kernels") or c.get("dtype") for c in compile_records)
    )
    if not has_keys:
        return
    print("-- kernels / precision --")
    if latest.get("kernels"):
        print(f"  kernel policy:  {latest['kernels']}")
    if latest.get("dtype"):
        print(f"  serve dtype:    {latest['dtype']}")
    if latest.get("mode") == "kernels":
        print(f"  fused-vs-stock: {latest.get('value')}x geomean "
              f"(fused {latest.get('fused_ms_total')}ms, stock "
              f"{latest.get('stock_ms_total')}ms"
              + (", interpret mode" if latest.get("interpret") else "")
              + ")")
        for sh in latest.get("shapes") or []:
            print(f"    {sh['name']:<22} fused {sh['fused_ms']:>8.3f}ms  "
                  f"stock {sh['stock_ms']:>8.3f}ms  {sh['speedup']}x")
    if by_kernel:
        total = sum(by_kernel.values()) or 1.0
        print("  executed FLOPs by kernel family:")
        for name, flops in sorted(by_kernel.items(), key=lambda kv: -kv[1]):
            print(f"    {name:<18} {flops / 1e9:>10.2f} GF  "
                  f"({flops / total:.1%})")


def report_mesh(latest: dict) -> None:
    """Mesh/sharding section: printed when records carry the mesh key
    (sharded serving, bench.py --mode serve with AF2TPU_SERVE_MESH).
    Shows the mesh shape, per-device memory (allocator HBM peaks when the
    backend exposes them, else the XLA memory-analysis program footprint
    from the compile records) and per-bucket compile times."""
    mesh = latest.get("mesh")
    compile_records = latest.get("compile_records") or []
    if not mesh and not any(c.get("mesh") for c in compile_records):
        return
    print(f"-- mesh sharding ({mesh or 'per-executable'}) --")
    if latest.get("mesh_devices"):
        print(f"  devices:        {int(latest['mesh_devices'])}")
    if latest.get("per_device_program_bytes"):
        print(
            "  per-device program footprint: "
            f"{latest['per_device_program_bytes'] / 2**20:.1f} MiB "
            "(XLA memory analysis: args + outputs + temps)"
        )
    hbm = sorted(
        (k, v) for k, v in latest.items()
        if k.startswith("hbm/device") and k.endswith("/peak_bytes")
    )
    for key, v in hbm:
        dev = key.split("/")[1]
        print(f"  {dev} HBM peak: {v / 2**30:.3f} GiB")
    if compile_records:
        print("  per-bucket executables:")
        for c in compile_records:
            extra = ""
            if c.get("program_bytes"):
                extra = f"  {c['program_bytes'] / 2**20:.1f} MiB/device"
            census = c.get("collectives") or {}
            if census:
                n = sum(v["count"] for v in census.values())
                moved = sum(v["bytes"] for v in census.values())
                extra += (f"  {n} collectives "
                          f"({moved / 2**10:.0f} KiB moved)")
            print(
                f"    bucket {c['bucket']:>5} batch {c['batch']} "
                f"mesh={c.get('mesh') or '-'}: compile "
                f"{_fmt_s(c['seconds'])}{extra}"
            )


def report_slo(latest: dict) -> None:
    """SLO section: the flattened ``slo/<spec>/<field>`` burn-rate keys a
    serve-async bench logs per spec (bench.py), plus the headline alert
    count — the multi-window verdicts the trace file carries as
    ``slo.alert`` instant events."""
    specs = sorted({
        k.split("/", 2)[1] for k in latest
        if k.startswith("slo/") and k.count("/") >= 2
    })
    if not specs and "slo_alerts" not in latest:
        return
    alerts = latest.get("slo_alerts")
    head = f", {int(alerts)} alert(s) fired" if alerts else ""
    print(f"-- SLO burn rates ({len(specs)} specs{head}) --")
    for spec in specs:
        def g(field, _s=spec):
            return latest.get(f"slo/{_s}/{field}")
        line = f"  {spec:<20}"
        fast, slow = g("fast_burn"), g("slow_burn")
        if fast is not None:
            line += f" fast burn {fast:>6.2f}  slow burn {slow:>6.2f}"
        bad, total = g("bad"), g("events")
        if total:
            line += f"  ({int(bad or 0)}/{int(total)} bad)"
        if g("alert"):
            line += "  ** ALERT **"
        print(line)


def report_hlo_contracts(path: str) -> list:
    """Static comm/memory contract section for a committed (or freshly
    ``--update``-written) hlo_contracts.json: per target the post-SPMD
    collective census, comm bytes beside FLOPs, the XLA program footprint
    and the HBM-budget verdict — the numbers ``analysis/hlo_audit.py
    --check`` diffs in CI, rendered for humans. Always returns [] (a
    malformed file raises into main()'s existing error path)."""
    with open(path) as f:
        doc = json.load(f)
    targets = doc.get("targets") or {}
    print(f"== hlo contracts {path}: {len(targets)} targets "
          f"(format {doc.get('format')}, jax {doc.get('jax_version')}, "
          f"{doc.get('n_devices')}x {doc.get('platform')}) ==")
    for name in sorted(targets):
        rec = targets[name]
        parts = rec.get("num_partitions", 1)
        head = f"  {name}: " + (
            f"{parts}-way partitioned" if rec.get("sharded")
            else "single-device"
        )
        if rec.get("program_bytes"):
            head += f", program {rec['program_bytes'] / 2**20:.2f} MiB/device"
        budget = rec.get("budget") or {}
        if budget.get("verdict"):
            head += f", budget {budget['verdict']}"
            if budget.get("headroom_frac") is not None:
                head += f" ({budget['headroom_frac']:+.1%} headroom)"
        print(head)
        census = rec.get("collectives") or {}
        if census:
            for kind in sorted(census):
                c = census[kind]
                print(f"    {kind:<20} x{c['count']:<4} "
                      f"{c['bytes'] / 2**10:>10.1f} KiB")
            ratio = rec.get("comm_bytes_per_flop")
            line = (f"    comm total: {rec.get('comm_bytes', 0) / 2**10:.1f} "
                    f"KiB moved")
            if ratio is not None:
                line += f"  ({ratio:.4g} bytes/FLOP)"
            print(line)
        elif rec.get("sharded"):
            print("    (no collectives — sharding constraints are inert)")
    return []


def report_concurrency_contracts(path: str) -> list:
    """Static layer-5 contract section for a committed (or freshly
    ``--update``-written) concurrency_contracts.json: the lock-order
    graph's named edges with their witness acquisition sites, and the
    per-class guard map — the shape ``analysis/concurrency.py --check``
    diffs in CI, rendered for humans. Always returns [] (a malformed
    file raises into main()'s existing error path)."""
    with open(path) as f:
        doc = json.load(f)
    edges = doc.get("lock_graph") or {}
    guards = doc.get("guards") or {}
    n_guards = sum(len(v) for v in guards.values())
    print(f"== concurrency contracts {path}: {len(edges)} lock-graph "
          f"edge(s), {n_guards} guarded attribute(s) across "
          f"{len(guards)} class(es) (format {doc.get('format')}) ==")
    if edges:
        print("  lock-order graph (acquire left before right):")
        for edge in sorted(edges):
            print(f"    {edge}    [{edges[edge]}]")
    else:
        print("  lock-order graph: no nested acquisitions (trivially "
              "acyclic)")
    for cls in sorted(guards):
        attrs = guards[cls]
        by_lock: dict = {}
        for attr, lock in attrs.items():
            by_lock.setdefault(lock, []).append(attr)
        print(f"  {cls}:")
        for lock in sorted(by_lock):
            print(f"    {lock} guards: {', '.join(sorted(by_lock[lock]))}")
    return []


def report_metrics(path: str) -> list:
    """Latest-value dump + per-domain sections. Returns the list of
    malformed-line descriptions (empty = clean) for main()'s summary —
    every parseable record is still reported."""
    records, errors = [], []
    with open(path) as f:
        for lineno, line in enumerate(f, start=1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError as e:
                errors.append(f"line {lineno}: {e.msg} ({line[:60]!r})")
                continue
            if isinstance(rec, dict):
                records.append(rec)
            else:
                errors.append(
                    f"line {lineno}: record is "
                    f"{type(rec).__name__}, not an object"
                )
    print(f"== metrics {path}: {len(records)} records ==")
    latest: dict = {}
    for rec in records:
        for k, v in rec.items():
            if k not in ("step", "time"):
                latest[k] = v
    for k in sorted(latest):
        # per-tensor numerics stats, per-device HBM peaks, SLO burn keys
        # and registry-snapshot flags are summarized by their sections
        # below, not dumped key by key
        if not k.startswith(("numerics/", "hbm/", "slo/", "slo.")) \
                and k != "registry":
            print(f"  {k} = {latest[k]}")

    report_train(records)
    report_scheduler(latest)
    report_variant_scan(latest)
    report_replay(latest)
    report_fleet(latest)
    report_slo(latest)
    report_mesh(latest)
    report_kernels(latest)

    compiles = latest.get("serve.compiles", latest.get("compiles"))
    hits = latest.get("serve.cache_hits", latest.get("cache_hits"))
    if compiles is not None and hits is not None:
        dispatches = compiles + hits
        rate = hits / dispatches if dispatches else 0.0
        print("-- compile/cache accounting --")
        print(f"  executable builds: {compiles}")
        print(f"  cache hits:        {hits}  "
              f"(hit rate {rate:.1%} of {dispatches} lookups)")
    if "hbm_peak_bytes" in latest:
        print(f"-- memory --\n  HBM peak: "
              f"{latest['hbm_peak_bytes'] / 2**30:.3f} GiB")
    return errors


def report_env() -> None:
    """The accelerator-relevant environment through the flight recorder's
    scrub: AXON_ keys dropped, secret-named values redacted."""
    print("== environment (scrubbed) ==")
    for k, v in sorted(scrub_env().items()):
        if k.startswith(("AF2TPU_", "JAX_", "XLA_", "TPU_", "LIBTPU")):
            print(f"  {k}={v}")


def main(argv=None) -> int:
    args = list(argv if argv is not None else sys.argv[1:])
    flags = [a for a in args if a.startswith("-")]
    paths = [a for a in args if not a.startswith("-")]
    if "--env" in flags:
        report_env()
    if not paths:
        if "--env" in flags:
            return 0
        print(__doc__.strip().splitlines()[2].strip(), file=sys.stderr)
        return 1
    rc = 0
    parse_errors: dict = {}
    for path in paths:
        try:
            kind = classify(path)
            reporter = {
                "trace": report_trace,
                "hlo-contracts": report_hlo_contracts,
                "concurrency-contracts": report_concurrency_contracts,
            }.get(kind, report_metrics)
            errs = reporter(path)
            if errs:
                parse_errors[path] = errs
        except (OSError, json.JSONDecodeError) as e:
            print(f"ERROR reading {path}: {type(e).__name__}: {e}",
                  file=sys.stderr)
            rc = 2
    if parse_errors:
        # structured, machine-grepped by CI: one header, per-file counts,
        # first few offending lines — and a nonzero exit so a truncated
        # artifact fails the job instead of silently under-reporting
        print("== PARSE ERRORS ==", file=sys.stderr)
        for path, errs in parse_errors.items():
            print(f"  {path}: {len(errs)} malformed line(s)",
                  file=sys.stderr)
            for err in errs[:5]:
                print(f"    {err}", file=sys.stderr)
            if len(errs) > 5:
                print(f"    ... {len(errs) - 5} more", file=sys.stderr)
        rc = 2
    return rc


if __name__ == "__main__":
    sys.exit(main())
