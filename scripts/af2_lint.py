"""CLI for the JAX graph-hygiene AST linter (analysis/lint.py).

    python scripts/af2_lint.py alphafold2_tpu/            # rc 1 on findings
    python scripts/af2_lint.py --json out.json alphafold2_tpu/ scripts/
    python scripts/af2_lint.py --select AF2L002,AF2L003 alphafold2_tpu/

Pure stdlib (no jax import), so the CI lint job runs in milliseconds and
before any backend exists. Exit codes: 0 clean, 1 findings, 2 usage error.
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from alphafold2_tpu.analysis import lint  # noqa: E402


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("paths", nargs="+", help="files or directories")
    parser.add_argument(
        "--select", default=None,
        help="comma-separated rule ids to report (default: all)",
    )
    parser.add_argument(
        "--severity", choices=lint.SEVERITIES, default=None,
        help="report only findings at this severity",
    )
    parser.add_argument(
        "--json", dest="json_path", default=None,
        help="also write the findings as JSON to this path",
    )
    parser.add_argument(
        "--list-rules", action="store_true", help="print the rule table"
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule, (severity, title) in sorted(lint.RULES.items()):
            print(f"{rule}  {severity:7s}  {title}")
        return 0

    select = None
    if args.select:
        select = {s.strip().upper() for s in args.select.split(",") if s.strip()}
        unknown = select - set(lint.RULES)
        if unknown:
            print(f"unknown rule id(s): {sorted(unknown)}", file=sys.stderr)
            return 2

    missing = [p for p in args.paths if not os.path.exists(p)]
    if missing:
        print(f"no such path(s): {missing}", file=sys.stderr)
        return 2

    findings = lint.lint_paths(args.paths, select=select)
    if args.severity:
        findings = [f for f in findings if f.severity == args.severity]

    for f in findings:
        print(f.format())
    if args.json_path:
        with open(args.json_path, "w", encoding="utf-8") as fh:
            fh.write(lint.findings_to_json(findings))
    n_err = sum(1 for f in findings if f.severity == "error")
    n_warn = len(findings) - n_err
    print(
        f"af2_lint: {len(findings)} finding(s) "
        f"({n_err} error, {n_warn} warning)"
    )
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
