"""Profile the benchmark training step on the attached accelerator and print
the top ops by self time, aggregated from the trace's XLA-op events.

Usage: python scripts/profile_step.py [overrides like AF2TPU_BENCH_* env]
Writes the raw jax.profiler trace under ~/.cache/af2tpu/profile (inspect with
tensorboard if available) and prints a text summary so no external viewer
is needed.
"""

from __future__ import annotations

import glob
import gzip
import json
import os
import sys
from collections import defaultdict

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import alphafold2_tpu

alphafold2_tpu.setup_platform()

import jax
import jax.numpy as jnp


def run_profiled_steps(trace_dir: str, n_steps: int = 3):
    from alphafold2_tpu.config import Config, DataConfig, ModelConfig, TrainConfig
    from alphafold2_tpu.data.pipeline import SyntheticDataset
    from alphafold2_tpu.train.loop import (
        build_model, device_put_batch, make_train_step, tiny_init_state,
    )

    e = lambda k, d: int(os.environ.get(k, d))
    cfg = Config(
        model=ModelConfig(
            dim=e("AF2TPU_BENCH_DIM", 256), depth=e("AF2TPU_BENCH_DEPTH", 2),
            heads=8, dim_head=64,
            max_seq_len=e("AF2TPU_BENCH_CROP", 256) * 2,
            msa_tie_row_attn=True, bfloat16=True,
        ),
        data=DataConfig(
            crop_len=e("AF2TPU_BENCH_CROP", 256),
            msa_depth=e("AF2TPU_BENCH_MSA_DEPTH", 16),
            msa_len=e("AF2TPU_BENCH_MSA_LEN", 256),
            batch_size=e("AF2TPU_BENCH_BATCH", 1),
            min_len_filter=e("AF2TPU_BENCH_CROP", 256),
        ),
        train=TrainConfig(gradient_accumulate_every=1, warmup_steps=10),
    )
    batch = next(iter(SyntheticDataset(cfg.data, seed=0)))
    model = build_model(cfg)
    state = tiny_init_state(cfg, model, batch)
    step = make_train_step(model, mesh=None)
    dev_batch = device_put_batch(batch)
    rng = jax.random.key(0)
    compiled = step.lower(state, dev_batch, rng).compile()

    for _ in range(3):  # warmup
        rng, r = jax.random.split(rng)
        state, metrics = compiled(state, dev_batch, r)
    jax.block_until_ready(state.params)

    with jax.profiler.trace(trace_dir):
        for _ in range(n_steps):
            rng, r = jax.random.split(rng)
            state, metrics = compiled(state, dev_batch, r)
        jax.block_until_ready(metrics["loss"])


def summarize(trace_dir: str, n_steps: int, top: int = 30):
    paths = glob.glob(
        os.path.join(trace_dir, "**", "*.trace.json.gz"), recursive=True
    )
    assert paths, f"no trace found under {trace_dir}"
    path = max(paths, key=os.path.getmtime)
    with gzip.open(path, "rt") as f:
        trace = json.load(f)

    # device traces emit several lanes per device pid (XLA Modules / Steps /
    # XLA Ops); only the per-op lane is summed — the others span the same
    # wall time and would double-count it
    by_name = defaultdict(float)
    total = 0.0
    device_pids = set()
    op_lanes = set()  # (pid, tid) of "XLA Ops" thread lanes
    for ev in trace.get("traceEvents", []):
        if ev.get("ph") != "M":
            continue
        if ev.get("name") == "process_name":
            pname = ev.get("args", {}).get("name", "")
            if "TPU" in pname or "GPU" in pname or "/device:" in pname:
                device_pids.add(ev["pid"])
        elif ev.get("name") == "thread_name":
            tname = ev.get("args", {}).get("name", "")
            if "XLA Ops" in tname:
                op_lanes.add((ev["pid"], ev.get("tid")))
    if not op_lanes:
        print(
            "WARNING: no 'XLA Ops' lane in trace — summing ALL device lanes; "
            "totals include module/step spans and overcount wall time 2-3x",
            file=sys.stderr,
        )
    for ev in trace.get("traceEvents", []):
        if ev.get("ph") != "X" or ev.get("pid") not in device_pids:
            continue
        if op_lanes and (ev["pid"], ev.get("tid")) not in op_lanes:
            continue
        name = ev.get("name", "?")
        dur = float(ev.get("dur", 0.0))  # microseconds
        by_name[name] += dur
        total += dur

    print(f"\ntrace: {path}")
    print(f"device op time total: {total/1e3:.2f} ms over {n_steps} steps "
          f"({total/1e3/max(n_steps,1):.2f} ms/step)\n")
    print(f"{'us/step':>10}  {'%':>5}  op")
    for name, dur in sorted(by_name.items(), key=lambda kv: -kv[1])[:top]:
        print(f"{dur/max(n_steps,1):10.0f}  {100*dur/total:5.1f}  {name[:110]}")


if __name__ == "__main__":
    import alphafold2_tpu

    # same default as tpu_session's stage_profile: per-user, not a fixed
    # world-writable /tmp path (and standalone + session runs share traces)
    trace_dir = os.environ.get(
        "AF2TPU_TRACE_DIR",
        os.path.join(alphafold2_tpu.user_cache_dir(), "profile"),
    )
    n = int(os.environ.get("AF2TPU_PROFILE_STEPS", 3))
    run_profiled_steps(trace_dir, n)
    summarize(trace_dir, n)
