"""Run the BASELINE.json benchmark-config suite on the attached chip and
write BENCH_SUITE.json.

The five configs come from BASELINE.json "configs" (mirrored in BASELINE.md),
scaled to ONE chip where the original calls for a pod (config 5). Each entry
reports residue-pairs/sec/chip for a full train step (fwd+bwd+opt) and the
step time; config 1 is the reference README functional config (forward+
backward only, the "CPU sanity" anchor — here timed on the accelerator).

Usage:
    python scripts/bench_suite.py            # all configs (slow: ~5 compiles)
    python scripts/bench_suite.py 2 4        # a subset by number
    AF2TPU_SUITE_SMOKE=1 python scripts/bench_suite.py   # tiny shapes (CI)
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import alphafold2_tpu

alphafold2_tpu.setup_platform()

import jax
import jax.numpy as jnp

SMOKE = os.environ.get("AF2TPU_SUITE_SMOKE") == "1"
ITERS = 3 if SMOKE else 8


def _timed_loop(run, warmup: int = 2) -> float:
    """Shared timing protocol: warmup calls, then ITERS timed calls.
    ``run()`` performs one step and returns an array to block on."""
    out = None
    for _ in range(warmup):
        out = run()
    if out is not None:
        jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(ITERS):
        out = run()
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / ITERS


def _train_throughput(cfg_kw, data_kw, label):
    from alphafold2_tpu.config import Config, DataConfig, ModelConfig, TrainConfig
    from alphafold2_tpu.data.pipeline import SyntheticDataset
    from alphafold2_tpu.train.loop import (
        build_model, device_put_batch, make_train_step, tiny_init_state,
    )

    cfg = Config(
        model=ModelConfig(**cfg_kw),
        data=DataConfig(**data_kw),
        train=TrainConfig(gradient_accumulate_every=1, warmup_steps=10),
    )
    batch = next(iter(SyntheticDataset(cfg.data, seed=0)))
    model = build_model(cfg)
    state = tiny_init_state(cfg, model, batch)
    step = make_train_step(model, mesh=None)
    dev_batch = device_put_batch(batch)
    rng = jax.random.key(0)
    compiled = step.lower(state, dev_batch, rng).compile()
    box = {"state": state, "rng": rng}

    def run():
        box["rng"], r = jax.random.split(box["rng"])
        box["state"], metrics = compiled(box["state"], dev_batch, r)
        return metrics["loss"]

    dt = _timed_loop(run)
    crop = data_kw["crop_len"]
    bsz = data_kw["batch_size"]
    return {
        "config": label,
        "step_ms": round(dt * 1e3, 2),
        "pairs_per_sec": round(bsz * crop * crop / dt, 1),
    }


def config_1():
    """Reference README functional config: fwd+bwd on 128-seq + 5x64 MSA."""
    from alphafold2_tpu.models import Alphafold2

    n, m, nm = (16, 2, 16) if SMOKE else (128, 5, 64)
    model = Alphafold2(dim=256, depth=2, heads=8, dim_head=64,
                      max_seq_len=2 * n, dtype=jnp.bfloat16)
    k = jax.random.key(0)
    seq = jax.random.randint(jax.random.fold_in(k, 1), (1, n), 0, 21)
    msa = jax.random.randint(jax.random.fold_in(k, 2), (1, m, nm), 0, 21)
    mask = jnp.ones((1, n), bool)
    msa_mask = jnp.ones((1, m, nm), bool)
    params = model.init(k, seq, msa, mask=mask, msa_mask=msa_mask)

    def loss(p):
        out = model.apply(p, seq, msa, mask=mask, msa_mask=msa_mask)
        return jnp.mean(out.astype(jnp.float32) ** 2)

    step = jax.jit(jax.value_and_grad(loss))
    compiled = step.lower(params).compile()
    dt = _timed_loop(lambda: compiled(params)[0])
    return {
        "config": f"1: README functional config fwd+bwd ({n} seq, {m}x{nm} MSA)",
        "step_ms": round(dt * 1e3, 2),
        "pairs_per_sec": round(n * n / dt, 1),
    }


def config_2():
    crop, msa = (16, 8) if SMOKE else (256, 64)
    depth = 2 if SMOKE else 12
    return _train_throughput(
        dict(dim=256 if not SMOKE else 64, depth=depth, heads=8,
             dim_head=64 if not SMOKE else 16, max_seq_len=2 * crop,
             remat=True, bfloat16=True),
        dict(crop_len=crop, msa_depth=1 if SMOKE else 8, msa_len=msa,
             batch_size=1, min_len_filter=crop),
        f"2: depth={depth} dense trunk, crop {crop}, {msa}-seq MSA pretraining",
    )


def config_3():
    crop = 16 if SMOKE else 512
    depth = 2 if SMOKE else 12
    sparse = (True, False) * (depth // 2)
    return _train_throughput(
        dict(dim=64 if SMOKE else 256, depth=depth, heads=8,
             dim_head=16 if SMOKE else 64, max_seq_len=crop,
             sparse_self_attn=sparse, cross_attn_compress_ratio=3,
             remat=True, bfloat16=True),
        dict(crop_len=crop, msa_depth=2 if SMOKE else 8,
             msa_len=16 if SMOKE else 128, batch_size=1,
             min_len_filter=crop),
        f"3: depth={depth} interleaved block-sparse + compress=3, crop {crop}",
    )


def config_4():
    crop, msa_d, msa_l = (16, 2, 16) if SMOKE else (384, 16, 128)
    from alphafold2_tpu.models import Alphafold2

    model = Alphafold2(
        dim=64 if SMOKE else 256, depth=1 if SMOKE else 2, heads=8,
        dim_head=16 if SMOKE else 64, max_seq_len=2 * crop,
        msa_tie_row_attn=True, template_attn_depth=1 if SMOKE else 2,
        use_se3_template_embedder=False, dtype=jnp.bfloat16,
    )
    T = 2 if SMOKE else 4
    k = jax.random.key(1)
    seq = jax.random.randint(jax.random.fold_in(k, 1), (1, crop), 0, 21)
    msa = jax.random.randint(jax.random.fold_in(k, 2), (1, msa_d, msa_l), 0, 21)
    t_seq = jax.random.randint(jax.random.fold_in(k, 3), (1, T, crop), 0, 21)
    t_coors = jax.random.normal(jax.random.fold_in(k, 4), (1, T, crop, 3)) * 10
    kw = dict(
        mask=jnp.ones((1, crop), bool),
        msa_mask=jnp.ones((1, msa_d, msa_l), bool),
        templates_seq=t_seq, templates_coors=t_coors,
        templates_mask=jnp.ones((1, T, crop), bool),
    )
    # init at tiny shapes (params depend only on the model config; the
    # template tables are sized by max_num_templates/max_seq_len) — skips
    # the full-size init compile, which at crop 384 + templates dominates
    tn, tm, tT = min(16, crop), min(2, msa_d), min(2, T)
    params = model.init(
        k, seq[:, :tn], msa[:, :tm, :tn],
        mask=kw["mask"][:, :tn],
        msa_mask=kw["msa_mask"][:, :tm, :tn],
        templates_seq=t_seq[:, :tT, :tn],
        templates_coors=t_coors[:, :tT, :tn],
        templates_mask=kw["templates_mask"][:, :tT, :tn],
    )

    def loss(p):
        out = model.apply(p, seq, msa, **kw)
        return jnp.mean(out.astype(jnp.float32) ** 2)

    step = jax.jit(jax.value_and_grad(loss))
    compiled = step.lower(params).compile()
    dt = _timed_loop(lambda: compiled(params)[0])
    return {
        "config": f"4: tied-row MSA + templates ({T}), crop {crop}, "
                  f"{msa_d}x{msa_l} MSA fwd+bwd",
        "step_ms": round(dt * 1e3, 2),
        "pairs_per_sec": round(crop * crop / dt, 1),
    }


def config_5():
    """End-to-end pipeline step (distogram -> MDS -> refine -> RMSD loss),
    reversible trunk. Pod config scaled to one chip."""
    crop = 16 if SMOKE else 128  # elongated x3 -> 384 trunk tokens
    depth = 2 if SMOKE else 8  # depth 24 of the pod config scaled to 1 chip
    from alphafold2_tpu.config import Config, DataConfig, ModelConfig, TrainConfig
    from alphafold2_tpu.data.pipeline import SyntheticDataset
    from alphafold2_tpu.train.end2end import (
        End2EndModel,
        init_end2end_state,
        make_end2end_step,
    )

    cfg = Config(
        model=ModelConfig(dim=64 if SMOKE else 128, depth=depth, heads=4,
                          dim_head=16 if SMOKE else 32, max_seq_len=6 * crop,
                          reversible=True, bfloat16=False),
        data=DataConfig(crop_len=crop, msa_depth=2, msa_len=crop,
                        batch_size=1, min_len_filter=crop),
        train=TrainConfig(gradient_accumulate_every=1, warmup_steps=10),
    )
    batch = next(iter(SyntheticDataset(cfg.data, seed=0)))
    model = End2EndModel(
        dim=cfg.model.dim, depth=cfg.model.depth, heads=cfg.model.heads,
        dim_head=cfg.model.dim_head, max_seq_len=cfg.model.max_seq_len,
        reversible=True, mds_iters=8 if SMOKE else 50,
    )
    from alphafold2_tpu.train.loop import device_put_batch

    from alphafold2_tpu.train.loop import tiny_batch_like

    state = init_end2end_state(cfg, model, tiny_batch_like(batch))
    step = make_end2end_step(model, mesh=None)
    dev_batch = device_put_batch(batch)
    rng = jax.random.key(0)
    compiled = step.lower(state, dev_batch, rng).compile()
    box = {"state": state, "rng": rng}

    def run():
        box["rng"], r = jax.random.split(box["rng"])
        box["state"], metrics = compiled(box["state"], dev_batch, r)
        return metrics["loss"]

    dt = _timed_loop(run)
    return {
        "config": f"5: end-to-end (distogram->MDS->SE3->RMSD), "
                  f"reversible depth={depth}, crop {crop}",
        "step_ms": round(dt * 1e3, 2),
        "pairs_per_sec": round(crop * crop / dt, 1),
    }


def config_6():
    """Bucketed batched serving: mixed-length request stream through
    serve.ServeEngine (one executable per ladder rung, batch 4)."""
    import numpy as np

    from alphafold2_tpu.config import Config, DataConfig, ModelConfig, ServeConfig
    from alphafold2_tpu.serve import ServeEngine, ServeRequest

    buckets = (8, 16) if SMOKE else (32, 48, 64)
    n_req = 6 if SMOKE else 24
    cfg = Config(
        model=ModelConfig(
            dim=32 if SMOKE else 64, depth=1 if SMOKE else 2, heads=4,
            dim_head=8 if SMOKE else 16, max_seq_len=3 * buckets[-1],
            bfloat16=jax.devices()[0].platform != "cpu",
        ),
        data=DataConfig(msa_depth=2 if SMOKE else 4),
        serve=ServeConfig(
            buckets=buckets, max_batch=4, mds_iters=8 if SMOKE else 50
        ),
    )
    engine = ServeEngine(cfg)
    rng = np.random.default_rng(0)
    alpha = "ACDEFGHIKLMNPQRSTVWY"
    reqs = [
        ServeRequest("".join(rng.choice(list(alpha), size=int(n))), seed=i)
        for i, n in enumerate(
            rng.integers(4, buckets[-1] + 1, size=n_req)
        )
    ]
    engine.warmup()
    t0 = time.perf_counter()
    results = engine.predict_many(reqs)
    wall = time.perf_counter() - t0
    lat = sorted(r.latency_s for r in results)
    stats = engine.stats()
    return {
        "config": f"6: bucketed serve engine, buckets {list(buckets)}, "
                  f"batch 4, {n_req} mixed-length requests",
        "step_ms": round(1e3 * wall / max(1, stats.get("serve.batches", 1)), 2),
        "pairs_per_sec": round(
            sum(len(r.seq) ** 2 for r in reqs) / wall, 1
        ),
        "residues_per_sec": round(sum(len(r.seq) for r in reqs) / wall, 1),
        "p50_ms": round(1e3 * lat[len(lat) // 2], 1),
        "p95_ms": round(1e3 * lat[min(len(lat) - 1, int(0.95 * len(lat)))], 1),
        "compiles": stats.get("serve.compiles", 0),
    }


CONFIGS = {"1": config_1, "2": config_2, "3": config_3, "4": config_4,
           "5": config_5, "6": config_6}


def main():
    args = sys.argv[1:]
    unknown = [a for a in args if a not in CONFIGS]
    if unknown:
        raise SystemExit(
            f"unknown config(s) {unknown}; choose from {sorted(CONFIGS)}"
        )
    which = args or list(CONFIGS)
    results = []
    for key in which:
        print(f"running config {key}...", flush=True)
        try:
            r = CONFIGS[key]()
        except Exception as e:  # report partial suites rather than nothing
            r = {"config": key, "error": f"{type(e).__name__}: {e}"[:300]}
        results.append(r)
        print(json.dumps(r), flush=True)
    device = jax.devices()[0].device_kind
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "BENCH_SUITE.json")
    write_results(path, results, device, SMOKE,
                  partial=which != list(CONFIGS))
    print(f"wrote {path}")


def write_results(path, results, device, smoke, partial):
    """Write the suite file. A subset (``partial``) run MERGES into the
    existing rows instead of clobbering the configs it did not run — but
    only when the rows are comparable (same device, same smoke setting);
    a first TPU run replaces CPU smoke rows wholesale."""
    merged = results
    if partial and os.path.exists(path):
        try:
            with open(path) as f:
                prior = json.load(f)
            if prior.get("device") == device and prior.get("smoke") == smoke:
                by_key = {
                    r["config"].split(":", 1)[0]: r
                    for r in prior.get("results", [])
                }
                for r in results:
                    by_key[r["config"].split(":", 1)[0]] = r
                merged = [by_key[k] for k in sorted(by_key)]
        except (OSError, ValueError, KeyError):
            pass  # unreadable prior file: write this run's rows alone
    with open(path, "w") as f:
        json.dump(
            {"device": device, "smoke": smoke, "results": merged}, f, indent=2
        )


if __name__ == "__main__":
    main()
