"""Evaluate a (checkpointed) model: distogram quality + realized-structure
metrics over held-out batches.

    python scripts/evaluate.py [--checkpoint dir] [--batches 8] [overrides...]

Reports the BASELINE.md quality bar (distogram lDDT) plus distogram
cross-entropy/accuracy and, with --realize, full-pipeline structure metrics
(MDS -> Kabsch -> RMSD/GDT/TM/lDDT vs the true CA trace). One JSON line at
the end for automation.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import alphafold2_tpu
from alphafold2_tpu.config import Config, parse_cli


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--checkpoint", default=None)
    ap.add_argument("--batches", type=int, default=4)
    ap.add_argument("--seed", type=int, default=1234)  # held-out stream
    ap.add_argument("--realize", action="store_true",
                    help="also run MDS realization + structure metrics")
    ap.add_argument("overrides", nargs="*")
    args = ap.parse_args()

    alphafold2_tpu.setup_platform()
    import jax
    import jax.numpy as jnp
    import numpy as np

    from alphafold2_tpu.data.pipeline import make_dataset
    from alphafold2_tpu.train.loop import (
        apply_features, build_model, device_put_batch,
        distogram_cross_entropy, tiny_init_state,
    )
    from alphafold2_tpu.utils import Kabsch, RMSD, TMscore, distogram_lddt, lddt
    from alphafold2_tpu.utils.structure import get_bucketed_distance_matrix

    cfg = parse_cli(args.overrides, Config())
    # same feature adaptation as training: PLM-trained checkpoints need the
    # embedds stream to restore and to be evaluated on what they were fed
    ds = apply_features(iter(make_dataset(cfg.data, seed=args.seed)), cfg)
    model = build_model(cfg)
    sample = next(ds)
    # params only (for the checkpoint restore target): tiny-sliced init
    # skips the full-size forward compile
    state = tiny_init_state(cfg, model, sample)
    params = state.params
    if args.checkpoint:
        from alphafold2_tpu.train.checkpoint import CheckpointManager

        mgr = CheckpointManager(args.checkpoint)
        try:
            params, step = mgr.restore_params(state.params)
            print(f"restored checkpoint step {step}")
        finally:
            mgr.close()

    @jax.jit
    def forward(params, batch):
        logits = model.apply(
            params, batch["seq"], batch.get("msa"), mask=batch["mask"],
            msa_mask=batch.get("msa_mask"), embedds=batch.get("embedds"),
        )
        labels = get_bucketed_distance_matrix(batch["coords"], batch["mask"])
        ce = distogram_cross_entropy(logits, labels)
        pred_bins = jnp.argmax(logits, -1)
        valid = labels != -100
        acc = jnp.sum((pred_bins == labels) & valid) / jnp.maximum(
            jnp.sum(valid), 1
        )
        dl = distogram_lddt(logits, batch["coords"], mask=batch["mask"])
        return ce, acc, jnp.mean(dl), logits

    ces, accs, dls, struct = [], [], [], []
    batch = sample
    for b in range(args.batches):
        dev = device_put_batch(batch)
        ce, acc, dl, logits = forward(params, dev)
        ces.append(float(ce)); accs.append(float(acc)); dls.append(float(dl))
        print(f"[batch {b}] ce={float(ce):.4f} bin_acc={float(acc):.4f} "
              f"distogram_lddt={float(dl):.4f}")
        if args.realize:
            from alphafold2_tpu.predict import realize_structure

            # CA-level distogram: no (N,CA,C) triplets, so the phi-based
            # chirality fix does not apply. Padding weights zeroed via mask.
            coords, _, _ = realize_structure(
                logits, iters=100, fix_mirror=False,
                mask=jnp.asarray(batch["mask"]),
            )
            for k in range(coords.shape[0]):
                # select valid residues by index — masks from real data can
                # have interior holes, a prefix slice would be wrong
                valid = np.where(np.asarray(batch["mask"][k]))[0]
                true = np.asarray(batch["coords"][k])[valid].T  # (3, V)
                pred = np.asarray(coords[k])[:, valid]
                a, t = Kabsch(pred, true)
                struct.append({
                    "rmsd": float(RMSD(np.asarray(a), np.asarray(t))[0]),
                    "tm": float(TMscore(np.asarray(a), np.asarray(t))[0]),
                    "lddt": float(lddt(np.asarray(a).T[None],
                                       np.asarray(t).T[None])[0]),
                })
        batch = next(ds)

    result = {
        "distogram_ce": sum(ces) / len(ces),
        "distogram_bin_accuracy": sum(accs) / len(accs),
        "distogram_lddt": sum(dls) / len(dls),
        "batches": args.batches,
    }
    if struct:
        for key in ("rmsd", "tm", "lddt"):
            result[f"structure_{key}"] = sum(s[key] for s in struct) / len(struct)
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
