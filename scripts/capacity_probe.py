"""Probe the largest training configuration that fits one chip.

BASELINE.md's second metric is "peak MSA x seq_len per chip: measure &
maximize". This driver binary-searches the largest crop that completes a
full training step (fwd+bwd+opt) on the attached accelerator for each of a
few engine configs (dense+remat, reversible, block-sparse, dense+remat-dots), at fixed MSA
16 x crop, and writes CAPACITY.json.

Each probe costs a compile, so the search is bounded (MAX_PROBES per
config). OOM is detected by catching RESOURCE_EXHAUSTED from compile or
execute.

Usage: python scripts/capacity_probe.py [--smoke]
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import alphafold2_tpu

alphafold2_tpu.setup_platform()

import jax

SMOKE = "--smoke" in sys.argv or os.environ.get("AF2TPU_SUITE_SMOKE") == "1"
MAX_PROBES = 3 if SMOKE else 6


def step_fits(crop: int, model_kw: dict) -> bool:
    """One full train step at this crop; False on device OOM."""
    import jax.numpy as jnp  # noqa: F401

    from alphafold2_tpu.config import Config, DataConfig, ModelConfig, TrainConfig
    from alphafold2_tpu.data.pipeline import SyntheticDataset
    from alphafold2_tpu.train.loop import (
        build_model, device_put_batch, make_train_step, tiny_init_state,
    )

    cfg = Config(
        model=ModelConfig(max_seq_len=2 * crop, **model_kw),
        data=DataConfig(crop_len=crop, msa_depth=2 if SMOKE else 16,
                        msa_len=crop, batch_size=1, min_len_filter=crop),
        train=TrainConfig(gradient_accumulate_every=1, warmup_steps=10),
    )
    try:
        batch = next(iter(SyntheticDataset(cfg.data, seed=0)))
        model = build_model(cfg)
        state = tiny_init_state(cfg, model, batch)
        step = make_train_step(model, mesh=None)
        state, metrics = step(state, device_put_batch(batch), jax.random.key(0))
        jax.block_until_ready(metrics["loss"])
        return bool(jax.numpy.isfinite(metrics["loss"]))
    except Exception as e:
        msg = str(e)
        if "RESOURCE_EXHAUSTED" in msg or "Out of memory" in msg or "OOM" in msg:
            return False
        raise


def probe(name: str, model_kw: dict, lo: int, hi: int) -> dict:
    """Largest crop in [lo, hi] that fits, by bounded bisection on
    multiples of 64 (128-lane friendly)."""
    quantum = 16 if SMOKE else 64
    results = {}

    def fits(crop):
        if crop not in results:
            print(f"  {name}: probing crop={crop}...", flush=True)
            results[crop] = step_fits(crop, model_kw)
            print(f"  {name}: crop={crop} -> "
                  f"{'fits' if results[crop] else 'OOM'}", flush=True)
        return results[crop]

    if not fits(lo):
        return {"engine": name, "max_crop": 0, "probes": results}
    best = lo
    for _ in range(MAX_PROBES - 1):
        if lo >= hi:
            break
        mid = ((lo + hi + quantum) // (2 * quantum)) * quantum
        mid = max(lo + quantum, min(mid, hi))
        if fits(mid):
            best, lo = mid, mid
        else:
            hi = mid - quantum
    return {"engine": name, "max_crop": best, "probes": {
        str(c): ok for c, ok in sorted(results.items())}}


def main():
    lo, hi = (16, 64) if SMOKE else (256, 1024)
    dim = 64 if SMOKE else 256
    dh = 16 if SMOKE else 64
    depth = 1 if SMOKE else 4
    engines = [
        ("dense+remat", dict(dim=dim, depth=depth, heads=8, dim_head=dh,
                             remat=True, msa_tie_row_attn=True,
                             bfloat16=True)),
        ("reversible", dict(dim=dim, depth=depth, heads=8, dim_head=dh,
                            reversible=True, msa_tie_row_attn=True,
                            bfloat16=True)),
        ("block-sparse+remat", dict(dim=dim, depth=depth, heads=8, dim_head=dh,
                                    remat=True, sparse_self_attn=True,
                                    msa_tie_row_attn=True, bfloat16=True)),
        # remat_policy="dots" keeps matmul outputs (backward skips their
        # recompute): how much peak crop does the MFU trade cost?
        ("dense+remat-dots", dict(dim=dim, depth=depth, heads=8, dim_head=dh,
                                  remat=True, remat_policy="dots",
                                  msa_tie_row_attn=True, bfloat16=True)),
    ]
    out = {"device": jax.devices()[0].device_kind, "smoke": SMOKE,
           "msa": "16 x crop", "results": []}
    for name, kw in engines:
        out["results"].append(probe(name, kw, lo, hi))
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "CAPACITY.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=2)
    print(json.dumps(out))
    print(f"wrote {path}")


if __name__ == "__main__":
    main()
