"""Structure-math validation on real(istic) structures — the notebook, as a CLI.

The reference validates its structure utilities interactively against real PDB
entries (notebooks/structure_utils_tests.ipynb: load 1h22/4k77, perturb,
check Kabsch/RMSD/GDT/TMscore behavior, MDS round-trip a true distance
matrix). Same checks here, runnable and assertable:

    python scripts/validate_structure_math.py [--pdb path/to/file.pdb]

Without ``--pdb`` a protein-like synthetic chain is used (this image has no
network to fetch RCSB entries); with it, any real structure's CA trace drives
the exact notebook protocol. Exits non-zero if any check fails.
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

# Host-side validation: run on CPU. Site hooks may pin jax.config.jax_platforms
# to an accelerator tunnel programmatically (overriding the env var), so force
# the config, not just the env.
if not os.environ.get("AF2TPU_TEST_TPU"):
    import jax

    jax.config.update("jax_platforms", "cpu")

from alphafold2_tpu.utils import GDT, Kabsch, MDScaling, RMSD, TMscore, cdist
from alphafold2_tpu.utils import pdb as pdbio


def load_ca(pdb_path: str | None, length: int = 96) -> np.ndarray:
    if pdb_path is not None:
        seq, ca = pdbio.load_pdb(pdb_path).ca_trace()
        if len(seq) < 4:
            raise SystemExit(
                f"{pdb_path}: found {len(seq)} CA atoms — not a usable "
                "protein structure (need >= 4 residues)"
            )
        print(f"loaded {pdb_path}: {len(seq)} residues")
        return ca.T.astype(np.float32)  # (3, N)
    from alphafold2_tpu.data.pipeline import _smooth_walk

    ca = _smooth_walk(np.random.default_rng(7), length)
    print(f"synthetic chain: {length} residues")
    return ca.T.astype(np.float32)


def check(name: str, ok: bool, detail: str) -> bool:
    print(f"  [{'ok' if ok else 'FAIL'}] {name}: {detail}")
    return ok


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--pdb", default=None, help="optional .pdb file to validate on")
    args = ap.parse_args()

    rng = np.random.default_rng(0)
    ca = load_ca(args.pdb)  # (3, N)
    n = ca.shape[1]
    ok = True

    # --- Kabsch recovers an arbitrary rigid transform exactly (notebook cells
    # 8-13: rotate+translate, align, expect RMSD ~ 0, TM ~ 1) ---
    print("rigid-transform recovery:")
    theta = 0.9
    rot = np.array(
        [[np.cos(theta), -np.sin(theta), 0],
         [np.sin(theta), np.cos(theta), 0],
         [0, 0, 1.0]], np.float32,
    )
    moved = rot @ ca + np.asarray([[5.0], [-3.0], [2.0]], np.float32)
    a, b = Kabsch(moved, ca)
    r0 = float(RMSD(np.asarray(a), np.asarray(b))[0])
    tm0 = float(TMscore(np.asarray(a), np.asarray(b))[0])
    ok &= check("kabsch rmsd", r0 < 1e-3, f"rmsd={r0:.2e}")
    ok &= check("kabsch tmscore", tm0 > 0.9999, f"tm={tm0:.6f}")

    # --- perturbation monotonicity (notebook cells 14-22: metrics degrade
    # with noise scale; GDT_HA <= GDT_TS always) ---
    print("noise-scale monotonicity:")
    scales = [0.1, 0.5, 1.0, 2.0]
    rmsds, tms, gts, ghs = [], [], [], []
    for s in scales:
        noisy = ca + rng.normal(scale=s, size=ca.shape).astype(np.float32)
        a, b = Kabsch(noisy, ca)
        a, b = np.asarray(a), np.asarray(b)
        rmsds.append(float(RMSD(a, b)[0]))
        tms.append(float(TMscore(a, b)[0]))
        gts.append(float(GDT(a, b, mode="TS")[0]))
        ghs.append(float(GDT(a, b, mode="HA")[0]))
    for s, r, t, g, h in zip(scales, rmsds, tms, gts, ghs):
        print(f"    noise={s:>4}: rmsd={r:6.3f} tm={t:.3f} gdt_ts={g:.3f} gdt_ha={h:.3f}")
    ok &= check("rmsd increases", all(np.diff(rmsds) > 0), f"{rmsds}")
    ok &= check("tm decreases", all(np.diff(tms) < 0), f"{tms}")
    ok &= check("gdt_ts decreases", all(np.diff(gts) <= 0), f"{gts}")
    ok &= check("gdt_ha <= gdt_ts", all(h <= g for h, g in zip(ghs, gts)), "")

    # --- MDS round-trip: true distance matrix -> 3D -> align -> high TM
    # (notebook cells 23-27) ---
    print("MDS round-trip from the true distance matrix:")
    dist = np.asarray(cdist(ca.T[None], ca.T[None]))[0]  # (N, N)
    coords3d, stress = MDScaling(dist, iters=200, tol=1e-7, fix_mirror=False)
    rec = np.asarray(coords3d)[0]  # (3, N)
    best_tm, best_rmsd = -1.0, np.inf
    for cand in (rec, rec * np.asarray([[1.0], [1.0], [-1.0]], np.float32)):
        a, b = Kabsch(cand, ca)
        t = float(TMscore(np.asarray(a), np.asarray(b))[0])
        if t > best_tm:
            best_tm = t
            best_rmsd = float(RMSD(np.asarray(a), np.asarray(b))[0])
    final_stress = float(np.asarray(stress)[-1, 0])
    print(f"    final stress={final_stress:.4f} rmsd={best_rmsd:.3f} tm={best_tm:.3f}")
    ok &= check("mds tmscore", best_tm > 0.8, f"tm={best_tm:.3f}")
    ok &= check("mds rmsd", best_rmsd < 0.25 * n ** 0.5, f"rmsd={best_rmsd:.3f}")

    # --- PDB export round-trip of the reconstruction ---
    print("PDB export round-trip:")
    s = pdbio.backbone_to_pdb("A" * n, rec.T)
    back = pdbio.parse_pdb(pdbio.to_pdb_string(s))
    _, ca2 = back.ca_trace()
    ok &= check(
        "pdb roundtrip", bool(np.allclose(ca2.T, rec, atol=1e-3)),
        f"max err={np.abs(ca2.T - rec).max():.2e}",
    )

    print("ALL OK" if ok else "FAILURES PRESENT")
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
