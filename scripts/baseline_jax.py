"""The JAX twin of baseline_torch.py: identical protocol, this framework.

One half of the matched head-to-head pair (BASELINE.md quality bar:
"distogram lDDT within 1% of the PyTorch baseline"). Every knob mirrors
scripts/baseline_torch.py exactly — same NpzShardDataset stream (same
seeds -> bit-identical numpy batches), same bucketed-distance labels, same
plain Adam(3e-4) with no warmup/clip/accum (the reference's optimizer,
train_pre.py:63), same eval protocol (held-out crop/MSA draws at
--eval-seed, optional --holdout-dir of never-trained chains), same JSON
record shape. The only intentional difference is the framework under test.

    python scripts/baseline_jax.py --data-dir shards/_h2h_train \
        --holdout-dir shards/_h2h_holdout --steps 600 --dim 256 --depth 2 \
        --heads 8 --dim-head 64 --crop 64 --msa-depth 16 --msa-len 64 \
        --tie-rows --eval-batches 16 --seed 0
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import alphafold2_tpu

alphafold2_tpu.setup_platform("cpu")  # matched-pair runs are host-side


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--data-dir", required=True)
    ap.add_argument("--steps", type=int, default=300)
    ap.add_argument("--dim", type=int, default=64)
    ap.add_argument("--depth", type=int, default=2)
    ap.add_argument("--heads", type=int, default=4)
    ap.add_argument("--dim-head", type=int, default=16)
    ap.add_argument("--crop", type=int, default=128)
    ap.add_argument("--msa-depth", type=int, default=1)
    ap.add_argument("--msa-len", type=int, default=0)  # 0 = crop
    ap.add_argument("--tie-rows", action="store_true")
    # inversion-based O(1)-activation-memory trunk engine (beyond-reference
    # at this scale: the reference's reversible mode exists but its repo
    # never trained it on real data)
    ap.add_argument("--reversible", action="store_true")
    # re-draw params under the reference's torch module defaults
    # (models/init.py) — isolates init distributions in the head-to-head
    ap.add_argument("--torch-init", action="store_true")
    # exact erf GELU (the reference's torch F.gelu) instead of the tanh
    # approximation — the remaining known systematic functional divergence
    ap.add_argument("--exact-gelu", action="store_true")
    ap.add_argument("--bf16", action="store_true")  # default f32 = torch CPU
    ap.add_argument("--holdout-dir", default=None)
    ap.add_argument("--batch-size", type=int, default=1)
    ap.add_argument("--lr", type=float, default=3e-4)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--eval-batches", type=int, default=8)
    ap.add_argument("--eval-seed", type=int, default=1234)
    ap.add_argument("--log-every", type=int, default=25)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    import optax
    from flax.training.train_state import TrainState

    from alphafold2_tpu.config import Config, DataConfig, ModelConfig
    from alphafold2_tpu.data.pipeline import NpzShardDataset
    from alphafold2_tpu.train.loop import (
        build_model,
        distogram_cross_entropy,
        tiny_batch_like,
    )
    from alphafold2_tpu.utils import distogram_lddt
    from alphafold2_tpu.utils.structure import get_bucketed_distance_matrix

    if args.torch_init and args.reversible:
        ap.error(
            "--torch-init is incompatible with --reversible: the reversible "
            "trunk's depth-stacked params would corrupt the fan_in "
            "computation (models/init.py)"
        )

    msa_len = args.msa_len or args.crop
    use_msa = args.msa_depth > 1

    def make_data_cfg(data_dir):
        return DataConfig(
            source="npz", data_dir=data_dir, crop_len=args.crop,
            batch_size=args.batch_size, msa_depth=args.msa_depth,
            msa_len=msa_len, min_len_filter=16, max_len_filter=10_000,
        )

    data_cfg = make_data_cfg(args.data_dir)
    cfg = Config(
        model=ModelConfig(
            dim=args.dim, depth=args.depth, heads=args.heads,
            dim_head=args.dim_head, max_seq_len=args.crop * 2,
            msa_tie_row_attn=args.tie_rows, bfloat16=args.bf16,
            reversible=args.reversible, gelu_exact=args.exact_gelu,
        ),
        data=data_cfg,
    )
    model = build_model(cfg)

    def model_kwargs(batch):
        kw = {"mask": jnp.asarray(batch["mask"])}
        if use_msa:
            kw["msa"] = jnp.asarray(batch["msa"])
            kw["msa_mask"] = jnp.asarray(batch["msa_mask"])
        return kw

    stream = iter(NpzShardDataset(data_cfg, seed=args.seed))
    sample = next(stream)
    # tiny-shape init (bit-identical params, none of the full-size compile)
    tiny = tiny_batch_like(sample if use_msa else
                           {k: v for k, v in sample.items()
                            if k in ("seq", "mask")})
    params = model.init(
        jax.random.key(args.seed), jnp.asarray(tiny["seq"]),
        jnp.asarray(tiny["msa"]) if use_msa else None,
        mask=jnp.asarray(tiny["mask"]),
        msa_mask=jnp.asarray(tiny["msa_mask"]) if use_msa else None,
    )
    if args.torch_init:
        from alphafold2_tpu.models.init import torch_match_reinit

        params = torch_match_reinit(params, jax.random.key(args.seed))
    # plain Adam, exactly torch.optim.Adam's defaults (betas 0.9/0.999,
    # eps 1e-8) — NOT the production warmup-cosine/clip/adamw of
    # train.loop.build_optimizer, which torch's side doesn't have
    state = TrainState.create(
        apply_fn=model.apply, params=params, tx=optax.adam(args.lr)
    )

    @jax.jit
    def train_step(state, batch):
        labels = get_bucketed_distance_matrix(batch["coords"], batch["mask"])

        def loss_fn(p):
            logits = state.apply_fn(
                p, batch["seq"], batch.get("msa"),
                mask=batch["mask"], msa_mask=batch.get("msa_mask"),
            )
            return distogram_cross_entropy(logits, labels)

        ce, grads = jax.value_and_grad(loss_fn)(state.params)
        return state.apply_gradients(grads=grads), ce

    @jax.jit
    def eval_logits(params, batch):
        return model.apply(
            params, batch["seq"], batch.get("msa"),
            mask=batch["mask"], msa_mask=batch.get("msa_mask"),
        )

    def device_batch(b):
        out = {"seq": jnp.asarray(b["seq"]), "mask": jnp.asarray(b["mask"]),
               "coords": jnp.asarray(b["coords"])}
        if use_msa:
            out["msa"] = jnp.asarray(b["msa"])
            out["msa_mask"] = jnp.asarray(b["msa_mask"])
        return out

    t0 = time.time()
    batch_np = sample
    step_ce = float("nan")
    for step in range(args.steps):
        state, ce = train_step(state, device_batch(batch_np))
        step_ce = float(ce)
        batch_np = next(stream)
        if step % args.log_every == 0:
            print(
                f"[jax baseline step {step}] ce={step_ce:.4f} "
                f"({time.time() - t0:.0f}s)",
                flush=True,
            )

    def eval_stream_metrics(dcfg, seed):
        lddts, ces = [], []
        es = iter(NpzShardDataset(dcfg, seed=seed))
        for _ in range(args.eval_batches):
            b = next(es)
            db = device_batch(b)
            logits = eval_logits(state.params, db)
            labels = get_bucketed_distance_matrix(db["coords"], db["mask"])
            ces.append(float(distogram_cross_entropy(logits, labels)))
            dl = distogram_lddt(logits, db["coords"], mask=db["mask"])
            lddts.append(float(np.mean(np.asarray(dl))))
        return float(np.mean(ces)), float(np.mean(lddts))

    eval_ce, eval_lddt = eval_stream_metrics(data_cfg, args.eval_seed)
    record = {
        "baseline": "alphafold2_tpu",
        "steps": args.steps,
        "config": {
            "dim": args.dim, "depth": args.depth, "heads": args.heads,
            "dim_head": args.dim_head, "crop": args.crop,
            "batch": args.batch_size, "lr": args.lr, "accum": 1,
            "msa_depth": args.msa_depth, "msa_len": msa_len,
            "tie_rows": args.tie_rows, "seed": args.seed,
            "dtype": "bf16" if args.bf16 else "f32",
            "engine": "reversible" if args.reversible else "default",
            "init": "torch" if args.torch_init else "flax",
            "gelu": "exact" if args.exact_gelu else "tanh",
        },
        "final_train_ce": round(step_ce, 4),
        "eval_ce": round(eval_ce, 4),
        "distogram_lddt": round(eval_lddt, 4),
        "seconds": round(time.time() - t0, 1),
    }
    if args.holdout_dir:
        hce, hdl = eval_stream_metrics(
            make_data_cfg(args.holdout_dir), args.eval_seed
        )
        record["holdout_eval_ce"] = round(hce, 4)
        record["holdout_distogram_lddt"] = round(hdl, 4)
    print(json.dumps(record))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
