"""Structure refinement via PyRosetta FastRelax — optional-dependency stub.

Keeps the same optional-stub shape as the reference (scripts/refinement.py:
import is warning-guarded :8-14, pdb<->pose conversion :22-54, and
``run_fast_relax`` loads a JSON config then raises NotImplementedError
:56-74). PyRosetta is licensed/closed and out of scope (SURVEY.md S2.4);
what IS implemented here is everything around the rosetta call so a user
with PyRosetta installed only fills in the marked section.
"""

from __future__ import annotations

import json
import warnings
from pathlib import Path

try:
    import pyrosetta  # type: ignore

    HAS_PYROSETTA = True
    pyrosetta.init(silent=True)
except ImportError:
    HAS_PYROSETTA = False
    warnings.warn(
        "pyrosetta not installed: FastRelax refinement unavailable. "
        "Install from https://www.pyrosetta.org/ (license required)."
    )

DEFAULT_CONFIG = {
    "scorefxn": "ref2015",
    "max_iter": 100,
    "constrain_relax_to_start_coords": True,
}


def pdb_to_pose(path: str):
    """Load a .pdb into a rosetta Pose (reference scripts/refinement.py:22-37)."""
    if not HAS_PYROSETTA:
        raise ImportError("pyrosetta required for pdb_to_pose")
    return pyrosetta.pose_from_pdb(path)


def pose_to_pdb(pose, path: str) -> str:
    """Write a rosetta Pose to .pdb (reference scripts/refinement.py:39-54)."""
    if not HAS_PYROSETTA:
        raise ImportError("pyrosetta required for pose_to_pdb")
    pose.dump_pdb(path)
    return path


def load_config(path: str | None = None) -> dict:
    cfg = dict(DEFAULT_CONFIG)
    if path is not None:
        cfg.update(json.loads(Path(path).read_text()))
    return cfg


def run_native_relax(pdb_in: str, pdb_out: str, iters: int = 200) -> str:
    """Dependency-free relaxation on the backbone (utils/relax.py): Adam on
    a bond-geometry + clash + restraint energy, jit-compiled — works on TPU
    with no external license. Beyond-reference: the reference's FastRelax
    was never implemented."""
    import sys

    sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
    import alphafold2_tpu

    alphafold2_tpu.setup_platform()  # AF2TPU_PLATFORM=cpu for host-side runs
    import jax
    import numpy as np

    from alphafold2_tpu.utils.pdb import load_pdb, replace_coords, to_pdb_string
    from alphafold2_tpu.utils.relax import fast_relax

    s = load_pdb(pdb_in)
    seq, bb, rows = s.backbone_trace(return_indices=True)  # (L, 3, 3)
    if len(seq) == 0:
        raise SystemExit(
            f"no complete N/CA/C backbone residues found in {pdb_in} "
            "(CA-only traces cannot be relaxed)"
        )
    flat = bb.reshape(1, -1, 3)
    result = jax.jit(lambda c: fast_relax(c, iters=iters))(flat)
    e0 = float(result.energy_history[0, 0])
    e1 = float(result.energy[0])
    print(f"native relax: energy {e0:.2f} -> {e1:.2f} over {iters} iters")
    # scatter relaxed backbone back into the original structure: chains,
    # numbering, sidechains, and non-backbone atoms are preserved verbatim
    new_coords = s.coords.copy()
    new_coords[rows.reshape(-1)] = np.asarray(result.coords[0])
    Path(pdb_out).write_text(to_pdb_string(replace_coords(s, new_coords)))
    return pdb_out


def run_fast_relax(pdb_in: str, pdb_out: str, config_path: str | None = None) -> str:
    """FastRelax a structure (reference scripts/refinement.py:56-74 raises
    NotImplementedError after loading its config; same contract here when
    pyrosetta is absent — use ``--native`` / :func:`run_native_relax` for
    the dependency-free path)."""
    config = load_config(config_path)
    if not HAS_PYROSETTA:
        raise NotImplementedError(
            f"FastRelax needs pyrosetta (config loaded: {config}); "
            "run with --native for the dependency-free jnp relaxation"
        )
    pose = pdb_to_pose(pdb_in)
    scorefxn = pyrosetta.create_score_function(config["scorefxn"])
    relax = pyrosetta.rosetta.protocols.relax.FastRelax(scorefxn)
    relax.max_iter(int(config["max_iter"]))
    relax.constrain_relax_to_start_coords(
        bool(config["constrain_relax_to_start_coords"])
    )
    relax.apply(pose)
    return pose_to_pdb(pose, pdb_out)


if __name__ == "__main__":
    import argparse

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("pdb_in")
    ap.add_argument("pdb_out")
    ap.add_argument("--config", default=None)
    ap.add_argument("--native", action="store_true",
                    help="dependency-free jnp relaxation (utils/relax.py)")
    ap.add_argument("--iters", type=int, default=200)
    args = ap.parse_args()
    if args.native:
        if args.config is not None:
            ap.error("--config applies to the pyrosetta path, not --native")
        run_native_relax(args.pdb_in, args.pdb_out, iters=args.iters)
    else:
        if args.iters != 200:
            ap.error("--iters applies to --native; use --config for pyrosetta")
        run_fast_relax(args.pdb_in, args.pdb_out, config_path=args.config)
