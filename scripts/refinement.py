"""Structure refinement via PyRosetta FastRelax — optional-dependency stub.

Keeps the same optional-stub shape as the reference (scripts/refinement.py:
import is warning-guarded :8-14, pdb<->pose conversion :22-54, and
``run_fast_relax`` loads a JSON config then raises NotImplementedError
:56-74). PyRosetta is licensed/closed and out of scope (SURVEY.md S2.4);
what IS implemented here is everything around the rosetta call so a user
with PyRosetta installed only fills in the marked section.
"""

from __future__ import annotations

import json
import warnings
from pathlib import Path

try:
    import pyrosetta  # type: ignore

    HAS_PYROSETTA = True
    pyrosetta.init(silent=True)
except ImportError:
    HAS_PYROSETTA = False
    warnings.warn(
        "pyrosetta not installed: FastRelax refinement unavailable. "
        "Install from https://www.pyrosetta.org/ (license required)."
    )

DEFAULT_CONFIG = {
    "scorefxn": "ref2015",
    "max_iter": 100,
    "constrain_relax_to_start_coords": True,
}


def pdb_to_pose(path: str):
    """Load a .pdb into a rosetta Pose (reference scripts/refinement.py:22-37)."""
    if not HAS_PYROSETTA:
        raise ImportError("pyrosetta required for pdb_to_pose")
    return pyrosetta.pose_from_pdb(path)


def pose_to_pdb(pose, path: str) -> str:
    """Write a rosetta Pose to .pdb (reference scripts/refinement.py:39-54)."""
    if not HAS_PYROSETTA:
        raise ImportError("pyrosetta required for pose_to_pdb")
    pose.dump_pdb(path)
    return path


def load_config(path: str | None = None) -> dict:
    cfg = dict(DEFAULT_CONFIG)
    if path is not None:
        cfg.update(json.loads(Path(path).read_text()))
    return cfg


def run_fast_relax(pdb_in: str, pdb_out: str, config_path: str | None = None) -> str:
    """FastRelax a structure (reference scripts/refinement.py:56-74 raises
    NotImplementedError after loading its config; same contract here when
    pyrosetta is absent)."""
    config = load_config(config_path)
    if not HAS_PYROSETTA:
        raise NotImplementedError(
            f"FastRelax needs pyrosetta (config loaded: {config})"
        )
    pose = pdb_to_pose(pdb_in)
    scorefxn = pyrosetta.create_score_function(config["scorefxn"])
    relax = pyrosetta.rosetta.protocols.relax.FastRelax(scorefxn)
    relax.max_iter(int(config["max_iter"]))
    relax.constrain_relax_to_start_coords(
        bool(config["constrain_relax_to_start_coords"])
    )
    relax.apply(pose)
    return pose_to_pdb(pose, pdb_out)


if __name__ == "__main__":
    import argparse

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("pdb_in")
    ap.add_argument("pdb_out")
    ap.add_argument("--config", default=None)
    args = ap.parse_args()
    run_fast_relax(args.pdb_in, args.pdb_out, config_path=args.config)
