"""Convert a directory of .pdb files into .npz training shards.

    python scripts/import_pdbs.py pdb_dir/ shards_out/ [--chain A]

Uses the dependency-free PDB codec (utils/pdb.py) to extract each file's
backbone: sequence tokens + N/CA/C coordinates (atom14-style (L, 3, 3)
array, slot 1 = CA). The output directory feeds training directly via
``data.source=npz data.data_dir=shards_out`` — the local real-data path the
reference delegates entirely to the sidechainnet package.
"""

from __future__ import annotations

import argparse
import glob
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from alphafold2_tpu import constants
from alphafold2_tpu.utils import pdb as pdbio

AA_INDEX = {a: i for i, a in enumerate(constants.AA_ALPHABET)}


def convert_structure(s: pdbio.PDBStructure, chain: str | None = None):
    """Structure -> (seq tokens (L,), backbone (L, 3, 3)) or None.

    Keeps residues that have all three backbone atoms (N, CA, C) in file
    order; unknown residue types become the pad token and are dropped.
    """
    keep = ~s.hetero
    if chain is not None:
        keep &= s.chain == chain
    sub = s.select(keep)
    seqs, bbs = [], []
    # group by (chain, resseq) in file order
    current = None
    atoms: dict = {}
    rows = list(zip(sub.chain, sub.resseq, sub.name, sub.resname, sub.coords))
    rows.append((None, None, None, None, None))  # flush sentinel
    for ch, ri, nm, rn, xyz in rows:
        key = (ch, ri)
        if key != current:
            if current is not None and all(k in atoms for k in ("N", "CA", "C")):
                aa = pdbio.THREE_TO_ONE.get(str(atoms["resname"]), None)
                if aa is not None and aa in AA_INDEX:
                    seqs.append(AA_INDEX[aa])
                    bbs.append(
                        np.stack([atoms["N"], atoms["CA"], atoms["C"]])
                    )
            current = key
            atoms = {}
        if nm in ("N", "CA", "C") and nm not in atoms:
            atoms[nm] = xyz
            atoms["resname"] = rn
    if len(seqs) < 4:
        return None
    return np.asarray(seqs, np.int32), np.stack(bbs).astype(np.float32)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("pdb_dir")
    ap.add_argument("out_dir")
    ap.add_argument("--chain", default=None, help="restrict to one chain id")
    args = ap.parse_args()

    os.makedirs(args.out_dir, exist_ok=True)
    paths = sorted(
        glob.glob(os.path.join(args.pdb_dir, "*.pdb"))
        + glob.glob(os.path.join(args.pdb_dir, "*.ent"))
    )
    if not paths:
        print(f"no .pdb files under {args.pdb_dir!r}", file=sys.stderr)
        return 1
    n_ok = 0
    for path in paths:
        # keep the extension in the shard name: 1abc.pdb and 1abc.ent in the
        # same directory must not overwrite each other's shard
        name = os.path.basename(path).replace(".", "_")
        try:
            result = convert_structure(pdbio.load_pdb(path), chain=args.chain)
        except (ValueError, IndexError) as e:
            print(f"skip {name}: unparseable ({e})", file=sys.stderr)
            continue
        if result is None:
            print(f"skip {name}: <4 complete backbone residues", file=sys.stderr)
            continue
        seq, backbone = result
        np.savez(
            os.path.join(args.out_dir, f"{name}.npz"),
            seq=seq, coords=backbone,
        )
        n_ok += 1
    print(f"imported {n_ok}/{len(paths)} structures -> {args.out_dir}")
    return 0 if n_ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
