"""PyTorch-baseline distogram training on the SAME data pipeline.

BASELINE.md's quality bar is "distogram lDDT within 1% of the PyTorch
baseline", but the reference publishes no numbers and its own training
driver needs the sidechainnet package (absent here). This script produces
the missing baseline number: it imports the reference package itself
(``--reference``, default /root/reference, read-only) and trains its
``Alphafold2`` on the same npz shards, batching, labels, optimizer
settings, and lDDT metric as this framework's ``train_pre.py`` — an
apples-to-apples pair of runs.

    python scripts/import_pdbs.py pdb_dir/ shards/
    python scripts/baseline_torch.py --data-dir shards/ --steps 300 \
        --dim 64 --depth 2 --crop 128

Two reference dependencies that this baseline never exercises are stubbed
so the import succeeds: ``mdtraj`` (PDB I/O helpers — we load npz shards
instead) and ``se3_transformer_pytorch`` (template sidechain encoder — the
distogram pretraining path never calls it, reference train_pre.py:79).
Prints one JSON line with the final cross-entropy and distogram lDDT.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
import types

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import alphafold2_tpu

alphafold2_tpu.setup_platform("cpu")  # jax side (labels/metrics) stays on host


def _install_stubs():
    import torch

    if "mdtraj" not in sys.modules:
        sys.modules["mdtraj"] = types.ModuleType("mdtraj")
    if "se3_transformer_pytorch" not in sys.modules:
        se3 = types.ModuleType("se3_transformer_pytorch")

        class SE3Transformer(torch.nn.Module):  # constructed, never called
            def __init__(self, **kwargs):
                super().__init__()

            def forward(self, *args, **kwargs):
                raise NotImplementedError(
                    "SE3 stub: the distogram baseline never runs templates"
                )

        se3.SE3Transformer = SE3Transformer
        sys.modules["se3_transformer_pytorch"] = se3


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--reference", default="/root/reference")
    ap.add_argument("--data-dir", required=True)
    ap.add_argument("--steps", type=int, default=300)
    ap.add_argument("--dim", type=int, default=64)
    ap.add_argument("--depth", type=int, default=2)
    ap.add_argument("--heads", type=int, default=4)
    ap.add_argument("--dim-head", type=int, default=16)
    ap.add_argument("--crop", type=int, default=128)
    # MSA stream (default off = the round-2 seq-only protocol): depth > 1
    # feeds the reference model a real MSA stream; shards without stored
    # alignments get the same seeded mutation-synthesized MSA as the jax
    # side (data/pipeline.py _fill_msa), so both frameworks see identical
    # arrays. --tie-rows enables the reference's tied-row attention
    # (alphafold2.py:141-151); crop must then not exceed the shortest
    # chain (its tied path forbids padded positions).
    ap.add_argument("--msa-depth", type=int, default=1)
    ap.add_argument("--msa-len", type=int, default=0)  # 0 = crop
    ap.add_argument("--tie-rows", action="store_true")
    # evaluate on a second shard dir of chains NEVER seen in training
    ap.add_argument("--holdout-dir", default=None)
    ap.add_argument("--batch-size", type=int, default=1)
    ap.add_argument("--lr", type=float, default=3e-4)  # train_pre.py:18
    ap.add_argument("--accum", type=int, default=1)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--eval-batches", type=int, default=8)
    ap.add_argument("--eval-seed", type=int, default=1234)  # held-out stream
    ap.add_argument("--log-every", type=int, default=25)
    args = ap.parse_args()

    import torch
    import torch.nn.functional as F

    sys.path.insert(0, args.reference)
    _install_stubs()
    from alphafold2_pytorch import Alphafold2  # the reference model itself

    from alphafold2_tpu.config import DataConfig
    from alphafold2_tpu.data.pipeline import NpzShardDataset
    from alphafold2_tpu.utils import distogram_lddt
    from alphafold2_tpu.utils.structure import get_bucketed_distance_matrix

    torch.manual_seed(args.seed)
    msa_len = args.msa_len or args.crop
    use_msa = args.msa_depth > 1

    def make_data_cfg(data_dir):
        return DataConfig(
            source="npz", data_dir=data_dir, crop_len=args.crop,
            batch_size=args.batch_size, msa_depth=args.msa_depth,
            msa_len=msa_len, min_len_filter=16, max_len_filter=10_000,
        )

    data_cfg = make_data_cfg(args.data_dir)

    model = Alphafold2(
        dim=args.dim, depth=args.depth, heads=args.heads,
        dim_head=args.dim_head, max_seq_len=args.crop * 2,
        msa_tie_row_attn=args.tie_rows,
    )
    optim = torch.optim.Adam(model.parameters(), lr=args.lr)

    def batches(seed, cfg=None):
        for batch in NpzShardDataset(cfg or data_cfg, seed=seed):
            seq = torch.from_numpy(batch["seq"]).long()
            mask = torch.from_numpy(batch["mask"]).bool()
            kw = {"mask": mask}
            if use_msa:
                kw["msa"] = torch.from_numpy(batch["msa"]).long()
                kw["msa_mask"] = torch.from_numpy(batch["msa_mask"]).bool()
            # identical labels to train_pre.py: jnp bucketing, -100 ignore
            labels_np = np.asarray(
                get_bucketed_distance_matrix(batch["coords"], batch["mask"])
            )
            yield seq, kw, torch.from_numpy(labels_np).long(), batch

    t0 = time.time()
    stream = batches(args.seed)
    model.train()
    step_ce = float("nan")
    for step in range(args.steps):
        optim.zero_grad()
        for _ in range(args.accum):
            seq, kw, labels, _ = next(stream)
            logits = model(seq, **kw)
            ce = F.cross_entropy(
                logits.reshape(-1, logits.shape[-1]), labels.reshape(-1),
                ignore_index=-100,
            )
            (ce / args.accum).backward()
        optim.step()
        step_ce = float(ce.detach())
        if step % args.log_every == 0:
            print(
                f"[torch baseline step {step}] ce={step_ce:.4f} "
                f"({time.time() - t0:.0f}s)",
                flush=True,
            )

    model.eval()

    def eval_stream_metrics(cfg, seed):
        lddts, ces = [], []
        stream = batches(seed, cfg)
        with torch.no_grad():
            for _ in range(args.eval_batches):
                seq, kw, labels, batch = next(stream)
                logits = model(seq, **kw)
                ces.append(float(F.cross_entropy(
                    logits.reshape(-1, logits.shape[-1]), labels.reshape(-1),
                    ignore_index=-100,
                )))
                dl = distogram_lddt(
                    logits.numpy(), batch["coords"], mask=batch["mask"]
                )
                lddts.append(float(np.mean(np.asarray(dl))))
        return float(np.mean(ces)), float(np.mean(lddts))

    eval_ce, eval_lddt = eval_stream_metrics(data_cfg, args.eval_seed)

    record = {
        "baseline": "pytorch-reference",
        "steps": args.steps,
        "config": {
            "dim": args.dim, "depth": args.depth, "heads": args.heads,
            "dim_head": args.dim_head, "crop": args.crop,
            "batch": args.batch_size, "lr": args.lr, "accum": args.accum,
            "msa_depth": args.msa_depth, "msa_len": msa_len,
            "tie_rows": args.tie_rows, "seed": args.seed,
        },
        "final_train_ce": round(step_ce, 4),
        "eval_ce": round(eval_ce, 4),
        "distogram_lddt": round(eval_lddt, 4),
        "seconds": round(time.time() - t0, 1),
    }
    if args.holdout_dir:
        hce, hdl = eval_stream_metrics(
            make_data_cfg(args.holdout_dir), args.eval_seed
        )
        record["holdout_eval_ce"] = round(hce, 4)
        record["holdout_distogram_lddt"] = round(hdl, 4)
    print(json.dumps(record))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
