"""One TPU-tunnel grant, every device-side artifact: run all measurement
stages sequentially in a SINGLE process.

The tunneled TPU relay serializes jax clients (one grant at a time, queued);
running the flagship bench, the bench-config suite, the capacity probe, the
compiled-Pallas parity proof, and the profiler trace as separate processes
costs one queue cycle each — and each failed/killed client can wedge the
relay. This driver does them all inside one backend session:

    python scripts/tpu_session.py [stage ...]    # default: all stages
    stages (default order): bench baseline pallas profile bisect
                            train_real capacity suite

Artifacts (repo root): TPU_SESSION.json (stage-by-stage results + errors),
plus whatever each stage writes (BENCH_SUITE.json, CAPACITY.json,
bench_baseline.json when the flagship bench succeeds on a real accelerator
and --no-rebaseline is not given, profile trace summary).

Every stage is best-effort: a failure is recorded and the next stage runs.
AF2TPU_SESSION_DEADLINE (seconds, default 10800) hard-bounds the whole
session with a watchdog that flushes partial results before exiting.

Tunnel-wedge recovery: the relay that proxies this process to the real TPU
runs *inside* the process, and a dropped upstream leaves every later jax
call hanging in C++ (observed: 50 min inside one remote_compile HTTP call).
A hung stage cannot be interrupted from Python, so when a stage exceeds
AF2TPU_STAGE_DEADLINE (seconds, default 2400) the watchdog records the
timeout, flushes, and **re-execs this script with the remaining stages** —
the fresh process brings up a fresh relay, and completed work is not lost:
results merge into the existing TPU_SESSION.json, and recompiles hit the
persistent compilation cache (alphafold2_tpu.enable_compile_cache).
"""

from __future__ import annotations

import hashlib
import importlib
import json
import os
import sys
import threading
import time
import traceback

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import alphafold2_tpu

alphafold2_tpu.setup_platform()  # AF2TPU_PLATFORM=cpu for host-side smokes

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
# AF2TPU_SESSION_OUT redirects the results file — e.g. a CPU-side
# train_real run must not clobber a concurrent real-TPU session's results
OUT_PATH = os.environ.get(
    "AF2TPU_SESSION_OUT", os.path.join(REPO, "TPU_SESSION.json")
)
_T0 = time.monotonic()
DEADLINE = int(os.environ.get("AF2TPU_SESSION_DEADLINE", 10800))
STAGE_DEADLINE = int(os.environ.get("AF2TPU_STAGE_DEADLINE", 2400))

RESULTS: dict = {"stages": {}, "device": None}
if os.environ.get("AF2TPU_SESSION_RESUME") and os.path.exists(OUT_PATH):
    # merge ONLY across watchdog relaunches (marker env set right before
    # os.execv): a re-exec'd session keeps completed stages' results and
    # re-run stages overwrite their entry. A fresh session must NOT inherit
    # a stale file from an earlier run (stage_baseline would re-record an
    # old bench measurement as the current baseline).
    try:
        with open(OUT_PATH) as _f:
            _prior = json.load(_f)
        RESULTS["stages"].update(_prior.get("stages", {}))
        RESULTS["device"] = _prior.get("device")
    except Exception:
        pass
_FLUSH_LOCK = threading.Lock()
# set by the stage loop for the stage watchdog: (name, start_monotonic,
# remaining stage names after the current one)
_CURRENT: dict = {"stage": None, "start": 0.0, "remaining": []}

# Flight recorder (observe.flightrec): incident dumps land beside the
# session artifacts, so whatever scp collects TPU_SESSION.json collects
# the scrubbed crash context too. An explicit AF2TPU_FLIGHTREC_DIR wins;
# the default keeps dumps out of the repo tree's committed files.
os.environ.setdefault(
    "AF2TPU_FLIGHTREC_DIR", os.path.join(REPO, "incidents")
)
from alphafold2_tpu.observe import flightrec  # noqa: E402

_FLIGHTREC = flightrec.maybe_install_from_env()


def _flush():
    # the deadline watchdog and the stage loop may flush concurrently
    with _FLUSH_LOCK:
        RESULTS["elapsed_seconds"] = round(time.monotonic() - _T0, 1)
        with open(OUT_PATH, "w") as f:
            json.dump(RESULTS, f, indent=2)


def _dump_incident(reason: str, extra=None) -> None:
    """Flight-recorder dump + surface the file path in RESULTS, so the
    session summary names exactly what to scp after a truncated window.
    Best-effort like everything else here (dump returns None on dup/IO)."""
    path = _FLIGHTREC.dump(reason, extra=extra) if _FLIGHTREC else None
    if path:
        RESULTS.setdefault("incidents", []).append(path)


# Stages that touch the (possibly tunneled) jax backend. After any backend
# death signature, each of these gets a CHEAP subprocess liveness probe
# (seconds, not the 1500-2400s stage deadline) before it is allowed to run
# — round 4 burned ~1.5 h of window on four stages against a dead tunnel
# (VERDICT r4 #1b). train_real's HOST-SIDE half (shard provisioning) still
# runs on fast-fail — see the train_real branch in _stage.
_JAX_STAGES = frozenset(
    ["first_light", "bench", "baseline", "pallas", "profile", "bisect",
     "train_real", "capacity", "suite"]
)
_BACKEND = {"suspect": False}
_DEATH_SIGNATURES = (
    "Unable to initialize backend",
    "stage deadline",
    "hung tunnel",
    "backend init never returned",
    "UNAVAILABLE",
)


def _backend_probe(
    timeout: int | None = None, env: dict | None = None
) -> tuple[bool, str]:
    """One tiny jax computation in a subprocess, hard-bounded. True iff the
    backend completes it. Cheap when the relay answers (~seconds); a hung
    tunnel costs `timeout`, not a stage deadline. The child inherits this
    process's environment (including the axon site hook) by default, so it
    probes the same backend the stages would use; ``env`` overrides for
    tests."""
    import subprocess

    timeout = timeout or int(os.environ.get("AF2TPU_LIVENESS_TIMEOUT", 120))
    try:
        r = subprocess.run(
            [sys.executable, "-c",
             "import jax, jax.numpy as jnp; "
             "assert float(jnp.ones((8, 8)).sum()) == 64.0"],
            timeout=timeout, capture_output=True, text=True, env=env,
        )
        if r.returncode == 0:
            return True, "probe ok"
        return False, f"probe rc={r.returncode}: {r.stderr[-300:]}"
    except subprocess.TimeoutExpired:
        return False, f"probe hung >{timeout}s (dead tunnel)"


def _stage_failure_marks_backend(name: str) -> None:
    rec = RESULTS["stages"].get(name, {})
    err = str(rec.get("error", ""))
    if any(s in err for s in _DEATH_SIGNATURES):
        _BACKEND["suspect"] = True


def _stage(name, fn):
    print(f"=== stage: {name} ===", flush=True)
    t0 = time.monotonic()
    if (
        _BACKEND["suspect"]
        and name in _JAX_STAGES
        and os.environ.get("AF2TPU_NO_LIVENESS_PROBE") != "1"
    ):
        alive, why = _backend_probe()
        if not alive:
            rec = {
                "ok": False,
                "seconds": round(time.monotonic() - t0, 1),
                "error": f"fast-failed: backend liveness {why} "
                "(a prior stage hit a backend death signature)",
                "fast_failed": True,
            }
            if name == "train_real":
                # the stage's shard provisioning is host-side and must not
                # die with the tunnel: do it NOW so the next window trains
                # immediately instead of re-discovering an empty cache dir
                try:
                    rec["shards_provisioned"] = ensure_real_shards()
                except Exception as e:
                    rec["provision_error"] = f"{type(e).__name__}: {e}"
            RESULTS["stages"][name] = rec
            print(f"stage {name} fast-failed: {why}", flush=True)
            _flush()
            return
        _BACKEND["suspect"] = False  # tunnel came back; resume normally
    if _FLIGHTREC:
        # stage timeline in every later incident dump's notes ring
        _FLIGHTREC.note("stage_start", stage=name)
    _CURRENT["stage"], _CURRENT["start"] = name, t0
    try:
        out = fn()
        RESULTS["stages"][name] = {
            "ok": True, "seconds": round(time.monotonic() - t0, 1),
            "result": out,
        }
    except Exception as e:
        RESULTS["stages"][name] = {
            "ok": False, "seconds": round(time.monotonic() - t0, 1),
            "error": f"{type(e).__name__}: {e}",
            "trace": traceback.format_exc()[-2000:],
        }
        print(f"stage {name} FAILED: {e}", flush=True)
    _CURRENT["stage"] = None
    _stage_failure_marks_backend(name)
    _flush()


def stage_first_light():
    """Smaller-config (crop 128) measurement FIRST: any healthy window
    yields a nonzero TPU number (+ mfu) even if the flagship compile later
    eats the stage budget (VERDICT r3 #1a). Cheap when the cache is warm;
    skipped once the flagship bench is green in this session file."""
    import bench

    if RESULTS["stages"].get("bench", {}).get("ok"):
        return "skipped (flagship bench already green)"
    rec = bench.main(overrides={"crop": 128, "msa_len": 128}, emit=False)
    RESULTS["device"] = __import__("jax").devices()[0].device_kind
    return rec


def stage_bench():
    import bench

    # same transient-init retry policy as bench.py's __main__: a flaky
    # tunnel window must not spend the whole grant with no flagship number
    attempts = max(1, int(os.environ.get("AF2TPU_BENCH_ATTEMPTS", 3)))
    for i in range(attempts):
        try:
            record = bench.main()
            break
        except RuntimeError as e:
            if "Unable to initialize backend" not in str(e) or i == attempts - 1:
                raise
            print(f"backend init unavailable (attempt {i + 1}/{attempts}); "
                  "retrying in 60s", flush=True)
            time.sleep(60)
    RESULTS["device"] = __import__("jax").devices()[0].device_kind
    return record


def stage_baseline():
    """Re-record bench_baseline.json from the flagship bench result (re-arms
    regression detection — the committed baseline predates in-graph
    stepping). Only on a real accelerator with a real measurement."""
    import jax

    import bench

    bench_res = RESULTS["stages"].get("bench", {})
    rec = bench_res.get("result") or {}
    if "--no-rebaseline" in sys.argv:
        return "skipped (--no-rebaseline)"
    if not bench_res.get("ok") or not rec.get("value"):
        raise RuntimeError("no flagship bench measurement to record")
    if rec.get("implausible") or rec.get("clock_suspect"):
        raise RuntimeError(
            "refusing to record an implausible or clock-suspect "
            "measurement as the baseline — the timed region did not sync "
            "with device completion"
        )
    if jax.devices()[0].platform == "cpu":
        raise RuntimeError("refusing to record a CPU run as the TPU baseline")
    if bench.config_overridden():
        raise RuntimeError(
            "refusing to record an env-overridden (non-flagship) config as "
            "the baseline — unset AF2TPU_BENCH_* size knobs"
        )
    baseline = {
        "metric": rec["metric"],
        "value": rec["value"],
        "unit": rec["unit"],
        "ingraph": rec["ingraph"],
        "device": jax.devices()[0].device_kind,
    }
    if "mfu" in rec:
        baseline["mfu"] = rec["mfu"]
    path = os.path.join(REPO, "bench_baseline.json")
    with open(path, "w") as f:
        json.dump(baseline, f, indent=2)
    return baseline


class _argv:
    """Sub-script mains parse sys.argv themselves — isolate them from this
    driver's stage arguments."""

    def __init__(self, *args):
        self.args = list(args)

    def __enter__(self):
        self.saved = sys.argv
        sys.argv = ["tpu_session"] + self.args

    def __exit__(self, *exc):
        sys.argv = self.saved


def stage_suite():
    mod = importlib.import_module("bench_suite")
    with _argv():
        mod.main()
    with open(os.path.join(REPO, "BENCH_SUITE.json")) as f:
        return json.load(f)


def stage_capacity():
    mod = importlib.import_module("capacity_probe")
    with _argv():
        mod.main()
    with open(os.path.join(REPO, "CAPACITY.json")) as f:
        return json.load(f)


def stage_pallas():
    """Compiled-mode (NOT interpret) Pallas block-sparse parity on the real
    chip: forward + grads vs the gather-based jnp oracle (VERDICT r1 #5)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from alphafold2_tpu.ops.sparse import (
        block_sparse_attention,
        block_sparse_attention_pallas,
    )

    if jax.devices()[0].platform == "cpu":
        raise RuntimeError("pallas stage needs the real chip (compiled mode)")

    # pre-hardware lowering gate (VERDICT r4 #2): the full Mosaic lowering
    # runs host-side in a scrubbed subprocess in ~1 min; a tiling/layout
    # violation fails HERE instead of wasting the chip window on a compile
    # that cannot succeed (round 4 lost its one pallas slot exactly so)
    import subprocess

    gate = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts",
                                      "check_tpu_lowering.py")],
        capture_output=True, text=True, timeout=1200,
    )
    if gate.returncode != 0:
        raise RuntimeError(
            "TPU lowering gate failed — compiled run would die in Mosaic "
            f"lowering; fix host-side first:\n{gate.stdout[-1500:]}"
            f"\nstderr: {gate.stderr[-1000:]}"
        )

    out = {"lowering_gate": "passed"}
    # the gate's input-builder IS this stage's configuration — one source
    # of truth, so what the gate certifies host-side is exactly what runs
    # here (import is safe: the gate's env scrub only fires as __main__).
    # scripts/ on sys.path like ensure_real_shards does it: the import must
    # also resolve when tpu_session is imported from outside scripts/
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    from check_tpu_lowering import _sparse_inputs

    for n, bs in ((512, 128), (1024, 128)):
        q, k, v, layout, mask = _sparse_inputs(n, bs)

        ref = block_sparse_attention(q, k, v, layout, bs, mask=mask)
        pal = jax.jit(
            lambda q, k, v: block_sparse_attention_pallas(
                q, k, v, layout, bs, mask=mask
            )
        )(q, k, v)
        fwd_err = float(jnp.max(jnp.abs(ref - pal)))

        def loss(impl):
            def f(q):
                o = impl(q, k, v, layout, bs, mask=mask)
                return jnp.sum(o**2)

            return f

        g_ref = jax.grad(loss(block_sparse_attention))(q)
        g_pal = jax.jit(jax.grad(loss(block_sparse_attention_pallas)))(q)
        bwd_err = float(jnp.max(jnp.abs(g_ref - g_pal)))
        assert np.isfinite(fwd_err) and np.isfinite(bwd_err)
        assert fwd_err < 2e-2 and bwd_err < 2e-1, (n, fwd_err, bwd_err)
        rec = {
            "fwd_max_err": fwd_err, "bwd_max_err": bwd_err, "compiled": True,
        }

        # A/B the three backends (fwd+bwd step time, compiled): the in-repo
        # Pallas kernels vs the stock splash kernel vs the jnp gather oracle
        from alphafold2_tpu.ops.sparse import block_sparse_attention_splash

        valid = mask[:, None, :, None]

        def timed(impl, iters=20):
            f = jax.jit(jax.grad(
                lambda q: jnp.sum((impl(q, k, v, layout, bs, mask=mask)
                                   * valid) ** 2)
            ))
            g = f(q)
            jax.block_until_ready(g)
            t0 = time.perf_counter()
            for _ in range(iters):
                g = f(q)
            jax.block_until_ready(g)
            return (time.perf_counter() - t0) / iters * 1e3  # ms

        # valid-region splash parity on the real chip (compiled, not
        # interpret — the CPU tests only ever ran interpret mode); held to
        # the same tolerances as the in-repo pallas kernels above
        spl = jax.jit(
            lambda q, k, v: block_sparse_attention_splash(
                q, k, v, layout, bs, mask=mask
            )
        )(q, k, v)
        rec["splash_fwd_max_err"] = float(
            jnp.max(jnp.abs((spl - ref) * valid))
        )

        def masked_loss(impl):
            def f(q):
                o = impl(q, k, v, layout, bs, mask=mask)
                return jnp.sum((o * valid) ** 2)

            return f

        g_vref = jax.grad(masked_loss(block_sparse_attention))(q)
        g_spl = jax.jit(
            jax.grad(masked_loss(block_sparse_attention_splash))
        )(q)
        rec["splash_bwd_max_err"] = float(jnp.max(jnp.abs(g_vref - g_spl)))
        assert rec["splash_fwd_max_err"] < 2e-2, rec
        assert rec["splash_bwd_max_err"] < 2e-1, rec
        rec["ms_pallas"] = round(timed(block_sparse_attention_pallas), 3)
        rec["ms_splash"] = round(timed(block_sparse_attention_splash), 3)
        rec["ms_jnp"] = round(timed(block_sparse_attention), 3)
        out[f"n{n}_block{bs}"] = rec
    return out


def ensure_real_shards() -> str:
    """HOST-SIDE shard provisioning for train_real — no TPU backend needed
    (VERDICT r4 #1c: round 4's train_real slot died instantly on an empty
    cache dir when the shards were buildable host-side the whole time).
    Returns the shard directory; raises only if nothing can be imported.

    Runs even when the backend is dead (the liveness fast-fail path calls
    it), so the NEXT window always finds shards waiting."""
    import shutil

    shard_dir = os.environ.get(
        "AF2TPU_REAL_SHARDS",
        os.path.join(alphafold2_tpu.user_cache_dir(), "real_shards"),
    )
    pdb_dir = os.environ.get("AF2TPU_REAL_PDB_DIR")
    have_shards = os.path.isdir(shard_dir) and any(
        f.endswith(".npz") for f in os.listdir(shard_dir)
    )
    if have_shards:
        return shard_dir
    if not pdb_dir:
        # default to the curated real-structure corpus — the reference's
        # own PDB fixtures, minus the save_to_check* duplicates (same
        # 482-res chain as 1h22_chain_1 rigid-transformed; training on
        # them would triple-weight one chain — BASELINE.md r3 provenance)
        curated = [
            "/root/reference/notebooks/data/1h22_protein.pdb",
            "/root/reference/notebooks/data/1h22_protein_chain_1.pdb",
            "/root/reference/notebooks/data/4k77_protein.pdb",
        ]
        available = [p for p in curated if os.path.exists(p)]
        if not available:
            raise RuntimeError(
                f"no .npz shards in {shard_dir}, no AF2TPU_REAL_PDB_DIR "
                "set, and the reference PDB fixtures are absent — "
                "nothing to train on"
            )
        pdb_dir = os.path.join(shard_dir, "_fixture_pdbs")
        os.makedirs(pdb_dir, exist_ok=True)
        for p in available:
            dst = os.path.join(pdb_dir, os.path.basename(p))
            if not os.path.exists(dst):
                shutil.copy(p, dst)
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    mod = importlib.import_module("import_pdbs")
    with _argv(pdb_dir, shard_dir):
        rc = mod.main()
    if rc:
        raise RuntimeError(
            f"import_pdbs failed (rc={rc}) for {pdb_dir}: no structures "
            "imported"
        )
    return shard_dir


def stage_train_real():
    """Flagship-dim training on REAL chains (VERDICT r1: quality evidence
    was toy-scale — dim 64): dim 256 / depth 2 / tied-row MSA on real PDB
    chains imported with the built-in codec, evaluated two ways:

    - ``eval_ce`` / ``distogram_lddt``: unseen crop/MSA draws of the
      TRAINING chains (in-distribution — the protocol of the BASELINE.md
      head-to-head, comparable to those rows; not chain-held-out)
    - ``holdout_*``: the same metrics on chains matching
      AF2TPU_HOLDOUT_PATTERN (default "4k77"), EXCLUDED from training —
      true generalization to an unseen chain

    Checkpoints every 500 steps, so an interrupted stage re-run resumes."""
    import shutil

    import jax
    import jax.numpy as jnp

    shard_dir = ensure_real_shards()

    steps = int(os.environ.get("AF2TPU_TRAIN_REAL_STEPS", 2000))
    crop = int(os.environ.get("AF2TPU_TRAIN_REAL_CROP", 256))
    holdout_pat = os.environ.get("AF2TPU_HOLDOUT_PATTERN", "4k77")

    # split: chains matching the holdout pattern never enter training
    all_shards = sorted(
        f for f in os.listdir(shard_dir) if f.endswith(".npz")
    )
    holdout = [f for f in all_shards if holdout_pat and holdout_pat in f]
    train_shards = [f for f in all_shards if f not in holdout]
    if not train_shards:
        train_shards, holdout = all_shards, []
    train_dir = os.path.join(shard_dir, "_train_split")
    holdout_dir = os.path.join(shard_dir, "_holdout_split")
    for d, files in ((train_dir, train_shards), (holdout_dir, holdout)):
        shutil.rmtree(d, ignore_errors=True)
        os.makedirs(d)
        for f in files:
            os.link(os.path.join(shard_dir, f), os.path.join(d, f))

    from alphafold2_tpu.config import Config, DataConfig, ModelConfig, TrainConfig
    from alphafold2_tpu.data.pipeline import make_dataset
    from alphafold2_tpu.train.loop import (
        build_model,
        distogram_cross_entropy,
        train,
    )
    from alphafold2_tpu.utils.metrics import distogram_lddt
    from alphafold2_tpu.utils.structure import get_bucketed_distance_matrix

    def data_cfg(data_dir):
        return DataConfig(
            source="npz", data_dir=data_dir, crop_len=crop,
            msa_depth=16, msa_len=crop, batch_size=1,
            min_len_filter=64, max_len_filter=600,
        )

    cfg = Config(
        model=ModelConfig(
            dim=256, depth=2, heads=8, dim_head=64, max_seq_len=crop * 2,
            msa_tie_row_attn=True, bfloat16=True,
        ),
        data=data_cfg(train_dir),
        train=TrainConfig(
            num_steps=steps, gradient_accumulate_every=1, warmup_steps=100,
            log_every=100, checkpoint_every=500,
            # key the resume checkpoint on the split + model shape: a stale
            # checkpoint from a different split would otherwise restore at
            # start_step=num_steps and report "holdout" metrics for chains
            # the restored weights actually trained on
            checkpoint_dir=os.path.join(
                os.environ.get(
                    "AF2TPU_TRAIN_REAL_CKPT",
                    os.path.join(
                        alphafold2_tpu.user_cache_dir(), "train_real_ckpt"
                    ),
                ),
                hashlib.sha1(
                    json.dumps([crop, steps, train_shards]).encode()
                ).hexdigest()[:10],
            ),
        ),
    )
    state = train(cfg)

    model = build_model(cfg)

    @jax.jit
    def eval_step(params, batch):
        logits = model.apply(
            params, batch["seq"], batch.get("msa"),
            mask=batch["mask"], msa_mask=batch.get("msa_mask"),
        )
        labels = get_bucketed_distance_matrix(batch["coords"], batch["mask"])
        ce = distogram_cross_entropy(logits, labels)
        dl = distogram_lddt(logits, batch["coords"], mask=batch["mask"])
        return ce, jnp.mean(dl)

    def eval_stream(data_dir, n_batches=8):
        it = iter(make_dataset(data_cfg(data_dir), seed=1234))
        ces, dls = [], []
        for _ in range(n_batches):
            b = {k: jnp.asarray(v) for k, v in next(it).items()}
            ce, dl = eval_step(state.params, b)
            ces.append(float(ce))
            dls.append(float(dl))
        return round(sum(ces) / len(ces), 4), round(sum(dls) / len(dls), 4)

    ce, dl = eval_stream(train_dir)
    out = {
        "config": f"dim=256 depth=2 heads=8 crop={crop} msa=16x{crop} "
        "tied-rows bf16",
        "steps": steps,
        "eval_ce": ce,  # unseen crop/MSA draws of the TRAINING chains
        "distogram_lddt": dl,
        "device": jax.devices()[0].device_kind,
        "train_shards": train_shards,
        "holdout_shards": holdout,
    }
    if holdout:
        # best-effort: e.g. every holdout chain outside the length filter
        # raises here, and that must not discard the training metrics above
        try:
            hce, hdl = eval_stream(holdout_dir)
            out["holdout_eval_ce"] = hce  # chains never seen in training
            out["holdout_distogram_lddt"] = hdl
        except Exception as e:
            out["holdout_error"] = f"{type(e).__name__}: {e}"
    return out


def stage_profile():
    mod = importlib.import_module("profile_step")
    trace_dir = os.environ.get(
        "AF2TPU_TRACE_DIR",
        os.path.join(alphafold2_tpu.user_cache_dir(), "profile"),
    )
    n = int(os.environ.get("AF2TPU_PROFILE_STEPS", 3))
    mod.run_profiled_steps(trace_dir, n_steps=n)
    mod.summarize(trace_dir, n, top=30)
    return {"trace_dir": trace_dir, "steps": n}


def stage_bisect():
    mod = importlib.import_module("bisect_perf")
    with _argv():
        mod.main()
    return "printed to stdout"


# cheap, high-value stages first: a tunnel that dies mid-session takes the
# rest of the session's budget with it, so the big-compile stages (suite's
# depth-12 configs, the capacity sweep) run last
STAGES = {
    "first_light": stage_first_light,
    "bench": stage_bench,
    "baseline": stage_baseline,
    "pallas": stage_pallas,
    "profile": stage_profile,
    "bisect": stage_bisect,
    "train_real": stage_train_real,
    "capacity": stage_capacity,
    "suite": stage_suite,
}


def main():
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    # snapshot now: the _argv context manager swaps sys.argv while sub-script
    # stages run, and the watchdog thread must not rebuild the relaunch
    # command from that mutable global (it would drop e.g. --no-rebaseline)
    flags = [a for a in sys.argv[1:] if a.startswith("-")]

    if _FLIGHTREC:
        # SIGTERM (window revoked, preemption): dump before the default
        # handler kills the process
        flightrec.install_signal_handler(_FLIGHTREC)

    def _watchdog():
        time.sleep(max(0.0, DEADLINE - (time.monotonic() - _T0)))
        RESULTS["deadline_exceeded"] = DEADLINE
        _dump_incident(
            "session_deadline",
            extra={"deadline_s": DEADLINE, "stage": _CURRENT["stage"]},
        )
        _flush()
        os._exit(75)  # nonzero: the session was truncated, not completed

    if DEADLINE > 0:
        threading.Thread(target=_watchdog, daemon=True).start()

    def _stage_watchdog():
        # a hung jax call (dead in-process relay) cannot be interrupted from
        # Python; re-exec with the remaining stages — fresh process, fresh
        # relay, prior results merged from TPU_SESSION.json, recompiles
        # served by the persistent compilation cache
        while True:
            time.sleep(30)
            name = _CURRENT["stage"]
            if name is None:
                continue
            if time.monotonic() - _CURRENT["start"] <= STAGE_DEADLINE:
                continue
            RESULTS["stages"][name] = {
                "ok": False,
                "seconds": round(time.monotonic() - _CURRENT["start"], 1),
                "error": f"stage deadline {STAGE_DEADLINE}s exceeded "
                "(hung tunnel?); relaunching for remaining stages",
            }
            _dump_incident(
                f"stage_deadline_{name}",
                extra={"stage": name, "deadline_s": STAGE_DEADLINE},
            )
            _flush()
            # retry the interrupted stage once in the relaunched session
            # (stages with checkpointing, e.g. train_real, resume where
            # they left off); a second timeout abandons it for good
            retried_key = f"AF2TPU_RETRIED_{name.upper()}"
            remaining = list(_CURRENT["remaining"])
            if not os.environ.get(retried_key):
                os.environ[retried_key] = "1"
                remaining = [name] + remaining
            relaunches = int(os.environ.get("AF2TPU_SESSION_RELAUNCHES", 4))
            elapsed = time.monotonic() - _T0
            budget_left = (
                DEADLINE - elapsed if DEADLINE > 0 else float("inf")
            )
            if (
                not remaining
                or relaunches <= 0
                or budget_left <= STAGE_DEADLINE / 2
            ):
                # no relaunch when the session budget is exhausted (a child
                # would overrun the configured bound), and NONZERO exit: a
                # stage was abandoned on timeout, and wrappers must be able
                # to tell this truncated session from a clean one
                os._exit(75)
            print(
                f"stage {name} exceeded {STAGE_DEADLINE}s; re-exec for "
                f"{remaining}", flush=True,
            )
            os.environ["AF2TPU_SESSION_RELAUNCHES"] = str(relaunches - 1)
            os.environ["AF2TPU_SESSION_RESUME"] = "1"
            if DEADLINE > 0:
                # the child's fresh _T0 must not reset the session bound:
                # hand it only the true remaining budget (never clamped up)
                os.environ["AF2TPU_SESSION_DEADLINE"] = str(int(budget_left))
            os.execv(
                sys.executable,
                [sys.executable, os.path.abspath(__file__)] + remaining + flags,
            )

    if STAGE_DEADLINE > 0:
        threading.Thread(target=_stage_watchdog, daemon=True).start()

    # Probe the relay's compile mode BEFORE the first stage touches jax
    # (ADVICE r2): stage_bench calls bench.main() in-process, which never
    # runs bench's __main__ preflight — facing a dead /remote_compile
    # endpoint, every stage would hang for the full STAGE_DEADLINE and the
    # relaunch would retry the same dead mode. Probing here re-execs this
    # driver into PALLAS_AXON_REMOTE_COMPILE=0 once, up front. Runs AFTER
    # the watchdog threads start: the probes (2 x 240s) must not outlive a
    # short session deadline with nothing flushed.
    from alphafold2_tpu.preflight import preflight_compile_mode

    # a relaunched session inherits the prior process's death evidence: its
    # first jax stage must re-prove the (fresh) relay alive with the cheap
    # probe instead of betting a stage deadline on it
    for _rec in RESULTS["stages"].values():
        if any(s in str(_rec.get("error", "")) for s in _DEATH_SIGNATURES):
            _BACKEND["suspect"] = True
            break

    RESULTS["preflight"] = preflight_compile_mode(
        # evaluated right before a re-exec, AFTER the probes have burned
        # their share of the budget
        remaining_fn=(
            (lambda: max(1, int(DEADLINE - (time.monotonic() - _T0))))
            if DEADLINE > 0 else None
        ),
        deadline_env_var="AF2TPU_SESSION_DEADLINE",
    )
    if RESULTS["preflight"] == "both_dead":
        # don't bet stage deadlines on a tunnel both probes just failed;
        # every jax stage now requires the cheap liveness probe to pass
        _BACKEND["suspect"] = True

    requested = [a for a in sys.argv[1:] if not a.startswith("-")]
    names = requested or list(STAGES)
    unknown = [n for n in names if n not in STAGES]
    assert not unknown, f"unknown stages {unknown}; have {list(STAGES)}"
    for i, name in enumerate(names):
        _CURRENT["remaining"] = names[i + 1:]
        _stage(name, STAGES[name])
    print(json.dumps({
        n: {k: v for k, v in s.items() if k != "trace"}
        for n, s in RESULTS["stages"].items()
    }, default=str)[:2000], flush=True)


if __name__ == "__main__":
    main()
