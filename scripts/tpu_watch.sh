#!/bin/bash
# Round-long TPU-tunnel watcher: probe every cycle; at the FIRST healthy
# window run the full measurement session (scripts/tpu_session.py), which
# warms the persistent compile cache and re-records bench_baseline.json so
# the driver's round-end bench.py lands a real number (VERDICT r2 #1).
#
# Run as a foreground background-task (NOT nohup/setsid — those get swept
# when the launching task ends). Probes try remote-compile first, then
# client-side compile: either one alive is a usable window (the session's
# startup preflight picks the right mode itself).
cd "$(dirname "$0")/.." || exit 1
# probe logic lives in ONE place (alphafold2_tpu.preflight); the watcher
# must agree with the session's own preflight about what "healthy" means.
# _probe_ok runs its jax subprocess under its own 240s timeout; the outer
# 300s timeout is a backstop, not the probe budget.
PROBE='import sys; from alphafold2_tpu.preflight import _probe_ok; sys.exit(0 if _probe_ok() else 1)'
CYCLES=${AF2TPU_WATCH_CYCLES:-60}
SLEEP=${AF2TPU_WATCH_SLEEP:-360}
for i in $(seq 1 "$CYCLES"); do
  echo "[watch] probe $i/$CYCLES $(date +%H:%M:%S)"
  ok=""
  if timeout 300 python -c "$PROBE" >/dev/null 2>&1; then
    ok="remote"
  elif PALLAS_AXON_REMOTE_COMPILE=0 timeout 300 python -c "$PROBE" >/dev/null 2>&1; then
    ok="client"
  fi
  if [ -n "$ok" ]; then
    echo "[watch] tunnel healthy ($ok-compile) at $(date +%H:%M:%S); launching tpu_session"
    AF2TPU_SESSION_DEADLINE=${AF2TPU_WATCH_SESSION_DEADLINE:-9000} \
      AF2TPU_REAL_PDB_DIR=${AF2TPU_REAL_PDB_DIR:-/root/reference/notebooks/data} \
      python scripts/tpu_session.py "$@"
    rc=$?
    echo "[watch] session rc=$rc"
    exit $rc
  fi
  sleep "$SLEEP"
done
echo "[watch] no healthy window in $CYCLES cycles"
exit 1
