#!/bin/bash
# Round-long TPU-tunnel watcher: probe every cycle; at each healthy window
# run the measurement session (scripts/tpu_session.py) for whatever stages
# are not yet green in TPU_SESSION.json, until all stages pass or the
# cycle budget is spent. The session warms the persistent compile cache
# and re-records bench_baseline.json, so the driver's round-end bench.py
# lands a real number (VERDICT r2 #1).
#
# Usage: tpu_watch.sh [stage ...] [--session-flags...]
#   Positional stage names RESTRICT the watcher to those stages (owed =
#   requested ∩ not-yet-green); flags are forwarded to tpu_session.py.
#
# Run as a foreground background-task (NOT nohup/setsid — those get swept
# when the launching task ends). Probes try remote-compile first, then
# client-side compile: either one alive is a usable window (the session's
# startup preflight picks the right mode itself).
cd "$(dirname "$0")/.." || exit 1
# probe logic lives in ONE place (alphafold2_tpu.preflight); the watcher
# must agree with the session's own preflight about what "healthy" means.
# _probe_ok runs its jax subprocess under its own 240s timeout; the outer
# 300s timeout is a backstop, not the probe budget.
PROBE='import sys; from alphafold2_tpu.preflight import _probe_ok; sys.exit(0 if _probe_ok() else 1)'
CYCLES=${AF2TPU_WATCH_CYCLES:-60}
SLEEP=${AF2TPU_WATCH_SLEEP:-360}
SESSION_OUT=${AF2TPU_SESSION_OUT:-TPU_SESSION.json}
# every probe/session line also lands in a repo file: when no healthy
# window opens all round, the probe log IS the round's perf artifact
WATCHLOG=${AF2TPU_WATCH_LOG:-TUNNEL_PROBES.log}

log() {
  echo "$@"
  echo "$@" >> "$WATCHLOG"
}

REQUESTED=""
FLAGS=()
for a in "$@"; do
  case "$a" in
    -*) FLAGS+=("$a") ;;
    *) REQUESTED="$REQUESTED $a" ;;
  esac
done

# a session file from an EARLIER round must not satisfy this round's stage
# accounting (or feed stage_baseline a stale bench measurement via the
# AF2TPU_SESSION_RESUME merge below) — archive it once at watcher start,
# without clobbering even older archives
if [ -f "$SESSION_OUT" ] && [ "${AF2TPU_WATCH_KEEP_SESSION:-0}" != "1" ]; then
  prev="${SESSION_OUT%.json}_prev_$(date +%Y%m%d_%H%M%S).json"
  mv "$SESSION_OUT" "$prev"
  log "[watch] archived pre-existing $SESSION_OUT -> $prev"
fi

remaining_stages() {
  # stages not yet ok in $SESSION_OUT, in session order, intersected with
  # the user's requested list (if any); stage_baseline consumes the bench
  # result of ITS OWN session run, so bench rides along whenever baseline
  # is still owed. Prints ERROR on any failure — the caller must not
  # confuse a broken accounting helper with "all stages green".
  python - "$SESSION_OUT" "$REQUESTED" <<'PY' || echo ERROR
import json, sys
# keep in sync with scripts/tpu_session.py STAGES
# (tests/test_tpu_watch.py asserts the two lists match)
order = ["first_light", "bench", "baseline", "pallas", "profile", "bisect",
         "train_real", "capacity", "suite"]
try:
    with open(sys.argv[1]) as f:
        done = json.load(f).get("stages", {})
except FileNotFoundError:
    done = {}
requested = sys.argv[2].split() if len(sys.argv) > 2 else []
want = [s for s in order if not requested or s in requested]
left = [s for s in want if not done.get(s, {}).get("ok")]
if "baseline" in left and "bench" not in left:
    left.insert(0, "bench")
print(" ".join(left))
PY
}

check_done() {
  REMAINING=$(remaining_stages)
  case "$REMAINING" in
    *ERROR*)
      log "[watch] stage accounting failed; treating all stages as owed"
      REMAINING="${REQUESTED:-first_light bench baseline pallas profile bisect train_real capacity suite}"
      return 1 ;;
    "")
      log "[watch] all session stages green in $SESSION_OUT; done"
      return 0 ;;
  esac
  return 1
}

for i in $(seq 1 "$CYCLES"); do
  check_done && exit 0
  log "[watch] probe $i/$CYCLES $(date +%H:%M:%S) (owed: $REMAINING)"
  ok=""
  if timeout 300 python -c "$PROBE" >/dev/null 2>&1; then
    ok="remote"
  elif PALLAS_AXON_REMOTE_COMPILE=0 timeout 300 python -c "$PROBE" >/dev/null 2>&1; then
    ok="client"
  fi
  if [ -n "$ok" ]; then
    log "[watch] tunnel healthy ($ok-compile) at $(date +%H:%M:%S); launching tpu_session $REMAINING"
    # no AF2TPU_REAL_PDB_DIR default here: train_real self-provisions the
    # CURATED fixture corpus (ensure_real_shards excludes the save_to_check
    # duplicates, which the raw notebooks/data directory would include)
    AF2TPU_SESSION_DEADLINE=${AF2TPU_WATCH_SESSION_DEADLINE:-9000} \
      AF2TPU_SESSION_RESUME=1 \
      python scripts/tpu_session.py $REMAINING ${FLAGS[@]+"${FLAGS[@]}"}
    log "[watch] session rc=$?"
    check_done && exit 0
  fi
  sleep "$SLEEP"
done
log "[watch] cycle budget spent; owed stages: $(remaining_stages)"
exit 1
