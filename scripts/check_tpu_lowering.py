"""Pre-hardware TPU lowering gate — thin shim over analysis/lowering.py.

The gate's substance (every Pallas kernel entry point lowered through the
full Mosaic pipeline on a CPU host, plus the mis-tiled negative control)
now lives in :mod:`alphafold2_tpu.analysis.lowering`, where the jaxpr
auditor folds it into the same findings stream (``python -m
alphafold2_tpu.analysis.jaxpr_audit --rules lowering``). This script stays
as the historical entry point because it owns the one thing a module
cannot: scrubbing the axon site hook from the environment and re-exec'ing
BEFORE jax is imported (the hook patches jax's backend lookup at
interpreter start and hangs any cross-platform lowering attempt through a
dead relay).

Run directly or via tests/test_pallas_lowering.py:

    python scripts/check_tpu_lowering.py          # exit 0 = gate green

Prints one JSON line per case; exit 0 iff every positive case lowers AND
the negative control is rejected.
"""

from __future__ import annotations

import os
import sys


sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _scrub_and_reexec() -> None:
    """Re-exec with the axon site hook removed, BEFORE jax is imported.

    The hook (PYTHONPATH sitecustomize) wraps jax's get_backend and routes
    even `lowering_platforms=('tpu',)` traces at the tunnel, where a dead
    relay hangs forever. Lowering needs no backend at all — a clean CPU
    process lowers for the tpu platform purely in Python (verified: the
    round-4 Mosaic block-shape error reproduces on CPU this way).

    Only runs when this file is the entry point: importers (e.g.
    scripts/tpu_session.py reusing the case input-builders) must never be
    re-exec'd out from under themselves.
    """
    if os.environ.get("AF2TPU_LOWERING_GATE_SCRUBBED") == "1":
        return
    from alphafold2_tpu.preflight import scrub_axon_env

    needs_scrub = (
        ".axon_site" in os.environ.get("PYTHONPATH", "")
        or os.environ.get("JAX_PLATFORMS", "") not in ("cpu", "")
        # match scrub_axon_env's own definition of a hooked environment:
        # it strips both AXON_ and PALLAS_AXON prefixes, so detection must
        # trigger on both or a hooked env could skip the scrub
        or any(
            k.startswith(("AXON_", "PALLAS_AXON")) for k in os.environ
        )
    )
    if not needs_scrub and "jax" not in sys.modules:
        os.environ["AF2TPU_LOWERING_GATE_SCRUBBED"] = "1"
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        return
    env = scrub_axon_env()
    env["AF2TPU_LOWERING_GATE_SCRUBBED"] = "1"
    os.execve(sys.executable, [sys.executable] + sys.argv, env)


if __name__ == "__main__":
    _scrub_and_reexec()

# re-exports: scripts/tpu_session.py imports the case input-builders from
# here ("the gate's input-builder IS the stage's configuration"), and the
# historical API surface of this script stays intact
from alphafold2_tpu.analysis.lowering import (  # noqa: E402,F401
    CASES,
    _is_mosaic_tiling_rejection,
    _sparse_inputs,
    lower_for_tpu,
    main,
    run_gate,
)

if __name__ == "__main__":
    sys.exit(main())
