#!/usr/bin/env python
"""Perf regression gate: compare a bench/serve record against a baseline.

    python scripts/bench_compare.py CURRENT [--baseline PATH]
        [--threshold metric=tol | metric=direction:tol ...]

``CURRENT`` is a JSON record as emitted by ``bench.py`` (any mode) —
a file path, or ``-`` to read the record from stdin (so the bench can pipe
straight in). ``--baseline`` defaults to the committed baseline for the
record's mode: ``bench_serve_baseline.json`` for serve records
(``bench_serve_mesh_baseline.json`` when the record carries a ``mesh``
key — sharded and single-device baselines coexist),
``bench_serve_async_baseline.json`` for serve-async records,
``bench_baseline.json`` otherwise. The per-metric threshold table is also
mode-keyed (``observe.regress.thresholds_for``): serve-async records gate
goodput and rejection rate beside the latency percentiles.

Prints ONE JSON line: ``{"verdict": "pass"|"regress"|"no-data", ...}`` with
per-metric comparisons (ratio vs threshold) or a no-data reason. The
comparison logic — record validity, device/metric/methodology keying,
thresholds — lives in ``alphafold2_tpu.observe.regress``.

Exit codes: 0 = pass or no-data (an invalid/incomparable record is a
diagnosis, not a regression), 1 = regression beyond threshold (fails the CI
step), 2 = unreadable/unparseable input. Pure host-side: no jax import.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from alphafold2_tpu.observe.regress import (
    compare,
    parse_threshold_overrides,
    thresholds_for,
)


def _load_record(path: str) -> dict:
    if path == "-":
        text = sys.stdin.read()
    else:
        with open(path) as f:
            text = f.read()
    # tolerate surrounding noise lines (the bench's contract is one JSON
    # line on stdout, but operators paste logs): take the first line that
    # parses as a JSON object
    try:
        return json.loads(text)
    except json.JSONDecodeError:
        for line in text.splitlines():
            line = line.strip()
            if line.startswith("{"):
                return json.loads(line)
        raise


def default_baseline_path(record: dict) -> str:
    if record.get("mode") == "serve" and record.get("mesh"):
        # mesh-keyed baseline: sharded serve records live beside (never
        # instead of) the single-device serve baseline, so CPU-mesh and
        # future TPU-pod numbers coexist behind the same gate
        name = "bench_serve_mesh_baseline.json"
    elif record.get("mode") == "serve" and record.get("dtype") == "bfloat16":
        # dtype-keyed baseline: the bf16 serving flagship competes against
        # its own committed record — precision changes are explicit diffs
        # against an explicit baseline, never a silent mutation of the f32
        # serve numbers
        name = "bench_serve_bf16_baseline.json"
    else:
        name = {
            "serve": "bench_serve_baseline.json",
            "serve-async": "bench_serve_async_baseline.json",
            "serve-scan": "bench_serve_scan_baseline.json",
            "serve-fleet": "bench_serve_fleet_baseline.json",
            "kernels": "bench_kernels_baseline.json",
        }.get(record.get("mode"), "bench_baseline.json")
    return os.path.join(REPO, name)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__.splitlines()[0], prog="bench_compare.py"
    )
    ap.add_argument("current", help="current record JSON path, or - for stdin")
    ap.add_argument(
        "--baseline",
        default=None,
        help="baseline record path (default: the committed baseline for the "
        "record's mode)",
    )
    ap.add_argument(
        "--threshold",
        action="append",
        default=[],
        metavar="METRIC=TOL",
        help="override a gate threshold, e.g. value=0.2 or p95_ms=lower:0.8",
    )
    args = ap.parse_args(argv)

    try:
        current = _load_record(args.current)
    except (OSError, json.JSONDecodeError) as e:
        print(f"ERROR reading current record {args.current!r}: "
              f"{type(e).__name__}: {e}", file=sys.stderr)
        return 2
    if not isinstance(current, dict):
        print(f"ERROR: current record is not a JSON object: {args.current!r}",
              file=sys.stderr)
        return 2

    try:
        # base table keyed by the record's shape (serve-async records gate
        # on goodput/rejection-rate, not just the train/serve metric set)
        thresholds = parse_threshold_overrides(
            args.threshold, base=thresholds_for(current)
        )
    except ValueError as e:
        print(f"ERROR: {e}", file=sys.stderr)
        return 2

    baseline_path = args.baseline or default_baseline_path(current)
    baseline = None
    if os.path.exists(baseline_path):
        try:
            with open(baseline_path) as f:
                baseline = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            print(f"ERROR reading baseline {baseline_path!r}: "
                  f"{type(e).__name__}: {e}", file=sys.stderr)
            return 2

    verdict = compare(current, baseline, thresholds)
    verdict["baseline_path"] = baseline_path
    print(json.dumps(verdict))
    if verdict["verdict"] == "regress":
        print(
            "REGRESSION: "
            + ", ".join(
                f"{c['name']} {c['current']:g} vs baseline "
                f"{c['baseline']:g} (ratio {c['ratio']}, "
                f"{c['direction']} better, tol {c['tolerance']})"
                for c in verdict["comparisons"]
                if not c["ok"]
            ),
            file=sys.stderr,
        )
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
