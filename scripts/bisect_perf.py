"""Microbenchmark the hot modules at flagship bench shapes on the attached
accelerator: per-module fwd+bwd time and achieved FLOPs/s, to locate where
the step's time goes when a full trace is unavailable (the axon tunnel does
not support jax.profiler traces).
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import alphafold2_tpu

alphafold2_tpu.setup_platform()

import jax
import jax.numpy as jnp

from alphafold2_tpu.observe.flops import step_flops
from alphafold2_tpu.ops.attention import Attention, AxialAttention, FeedForward

CROP = int(os.environ.get("AF2TPU_BENCH_CROP", 256))
MSA_D = int(os.environ.get("AF2TPU_BENCH_MSA_DEPTH", 16))
MSA_L = int(os.environ.get("AF2TPU_BENCH_MSA_LEN", 256))
DIM = 256
ITERS = 10


def timed(name, module, *args, **kwargs):
    params = module.init(jax.random.key(0), *args, **kwargs)

    def loss(p):
        out = module.apply(p, *args, **kwargs)
        return jnp.sum(out.astype(jnp.float32) ** 2)

    step = jax.jit(jax.value_and_grad(loss))
    compiled = step.lower(params).compile()
    flops = step_flops(compiled) or 0.0  # observe.flops: the one parser

    compiled(params)[0].block_until_ready()
    t0 = time.perf_counter()
    for _ in range(ITERS):
        l, _ = compiled(params)
    l.block_until_ready()
    dt = (time.perf_counter() - t0) / ITERS
    print(f"{name:42s} {dt*1e3:8.2f} ms  {flops/dt/1e12:6.1f} TF/s  "
          f"({flops/1e9:.1f} GFLOP)")
    return dt


def main():
    dt = jnp.bfloat16
    k = jax.random.key(1)
    pair = jax.random.normal(k, (1, CROP, CROP, DIM), dt)
    msa = jax.random.normal(k, (1, MSA_D, MSA_L, DIM), dt)
    pair_flat = pair.reshape(1, CROP * CROP, DIM)
    msa_flat = msa.reshape(1, MSA_D * MSA_L, DIM)

    print(f"crop={CROP} msa={MSA_D}x{MSA_L} dim={DIM} device="
          f"{jax.devices()[0].device_kind}\n")

    total = 0.0
    total += timed(
        "pair AxialAttention (grid-native, flash)",
        AxialAttention(dim=DIM, heads=8, dim_head=64, dtype=dt), pair,
    )
    # the A/B for the grid-native default: the flat route materializes a
    # transpose of the whole pair map for the column pass. 3 extra compiles
    # of the hottest module — AF2TPU_BENCH_AB=0 skips once the question is
    # settled on real hardware.
    if os.environ.get("AF2TPU_BENCH_AB", "1") == "1":
        timed(
            "pair AxialAttention (flat route, flash)",
            AxialAttention(dim=DIM, heads=8, dim_head=64, grid_native=False,
                           dtype=dt),
            pair,
        )
        timed(
            "pair AxialAttention (grid-native, no flash)",
            AxialAttention(dim=DIM, heads=8, dim_head=64, use_flash=False,
                           dtype=dt),
            pair,
        )
        timed(
            "pair AxialAttention (flat route, no flash)",
            AxialAttention(dim=DIM, heads=8, dim_head=64, use_flash=False,
                           grid_native=False, dtype=dt),
            pair,
        )
    total += timed(
        "msa AxialAttention tied",
        AxialAttention(dim=DIM, heads=8, dim_head=64, tie_row_attn=True, dtype=dt),
        msa,
    )
    total += timed(
        "cross pair<-msa (flash)",
        Attention(dim=DIM, heads=8, dim_head=64, dtype=dt),
        pair_flat, context=msa_flat,
    )
    total += timed(
        "cross msa<-pair (flash)",
        Attention(dim=DIM, heads=8, dim_head=64, dtype=dt),
        msa_flat, context=pair_flat,
    )
    total += timed(
        "pair FeedForward",
        FeedForward(dim=DIM, dtype=dt), pair,
    )
    total += timed(
        "msa FeedForward",
        FeedForward(dim=DIM, dtype=dt), msa,
    )
    # per trunk layer = pair axial + msa axial + 2 cross + 2 FF (one config of
    # the two axial baselines applies)
    print(f"\nsum of micro-times (one of each): {total*1e3:.2f} ms")


if __name__ == "__main__":
    main()
