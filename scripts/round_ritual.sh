#!/bin/bash
# Once-per-round verification ritual (VERDICT r2 weak #5/#6): the canonical
# suite with native/ built, the AF2TPU_HEAVY 768-crop 2D-grid + block-sparse
# + remat composition proof, and the driver-visible multichip dryrun.
# Everything is hermetic CPU — no tunnel dependency.
set -e
cd "$(dirname "$0")/.."
echo "== full suite (builds native/) =="
bash run_tests.sh
echo "== heavy composition test (~7 min) =="
AF2TPU_HEAVY=1 env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
  XLA_FLAGS="--xla_force_host_platform_device_count=8" \
  python -m pytest tests/test_grid_parallel.py -q
echo "== multichip dryrun (8 virtual devices) =="
env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
  python -c "import __graft_entry__ as g; g.dryrun_multichip(8)"
echo "== round ritual complete =="
