"""Predict a 3D structure from a sequence and write it as a PDB file.

    python scripts/predict.py --seq MKTAYIAKQRQISFVKSHFSRQLEERLGLIEVQ \
        [--checkpoint ckpt_dir] [--out pred.pdb] [model.dim=256 ...]

Runs the full pipeline (trunk -> distogram -> MDS -> sidechains -> SE(3)
refine — the flow the reference only sketches) and exports N/CA/C backbone
records via the dependency-free PDB writer. Without --checkpoint the model
is randomly initialized: the geometry is meaningless but the pipeline is
real, which is exactly what an integration smoke needs.
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import alphafold2_tpu
from alphafold2_tpu.config import Config, parse_cli


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--seq", required=True, help="one-letter AA sequence")
    ap.add_argument("--checkpoint", default=None, help="training checkpoint dir")
    ap.add_argument("--out", default="prediction.pdb")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("overrides", nargs="*", help="config overrides key=value")
    args = ap.parse_args()

    alphafold2_tpu.setup_platform()
    from alphafold2_tpu.predict import predict
    from alphafold2_tpu.utils import pdb as pdbio

    cfg = parse_cli(args.overrides, Config())
    pred = predict(cfg, args.seq, checkpoint_dir=args.checkpoint, seed=args.seed)
    pdbio.save_pdb(pred.to_pdb(args.seq), args.out)
    ca = pred.backbone[:, 1]
    import numpy as np

    d = np.linalg.norm(ca[1:] - ca[:-1], axis=-1)
    print(
        f"wrote {args.out}: {len(args.seq)} residues, "
        f"mean consecutive CA-CA distance {d.mean():.2f} A, "
        f"mean confidence weight {pred.weights.mean():.3f}"
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
