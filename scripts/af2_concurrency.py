"""CLI for static layer 5: the concurrency auditor + knob registry.

    python scripts/af2_concurrency.py                       # audit + knobs
    python scripts/af2_concurrency.py --graph               # lock-order graph
    python scripts/af2_concurrency.py --check               # vs committed
                                                            #  concurrency_contracts.json
    python scripts/af2_concurrency.py --update              # re-baseline
    python scripts/af2_concurrency.py --knobs-markdown      # README tables

Thin wrapper over ``alphafold2_tpu.analysis.concurrency`` (lock-order
graph, guard contracts, thread/queue lifecycles — AF2C rules) and
``alphafold2_tpu.analysis.knobs`` (AF2TPU_* env-knob registry — AF2K
rules). Pure stdlib (no jax import), so the CI job runs in milliseconds
and before any backend exists. Exit codes: 0 clean, 1 findings/drift,
2 missing baseline or usage error. The exit code is the max of the two
audits so one command gates both.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from alphafold2_tpu.analysis import concurrency, knobs  # noqa: E402


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if "--knobs-markdown" in argv:
        return knobs.main(["--markdown"])
    if "--no-knobs" in argv:
        return concurrency.main([a for a in argv if a != "--no-knobs"])
    rc = concurrency.main(argv)
    # graph/update/list-rules are single-purpose introspection modes;
    # the knob audit rides along only on the gating paths
    if any(a in argv for a in ("--graph", "--update", "--list-rules")):
        return rc
    knob_rc = knobs.main([a for a in argv if a in ("--json",)])
    return max(rc, knob_rc)


if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:
        # piping into `head` closes stdout early; that's not an error
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        sys.exit(0)
