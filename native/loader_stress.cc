// Concurrency stress harness for the native data loader, built for running
// under ThreadSanitizer (make tsan) — the race-detection tier for the one
// genuinely concurrent component in the framework (worker threads + ordered
// bounded queue in dataloader.cc). Exercises: many producers vs a consumer,
// tiny admission window (maximum contention on the flow-control predicate),
// mid-stream destruction with workers blocked on both condition variables.
//
// Usage: ./loader_stress [rounds]   — exits 0 iff batches arrive in order
// and all shutdown paths join cleanly. CI/test runs it compiled with
// -fsanitize=thread so any data race in dataloader.cc fails the build.

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <vector>

extern "C" {
void* af2_loader_create(int batch, int crop_len, int msa_depth, int msa_len,
                        int min_len, uint64_t seed, int num_workers,
                        int queue_capacity, int num_buckets, float min_dist,
                        float max_dist, int32_t ignore_index);
void* af2_real_loader_create(int n_chains, const int32_t* lens,
                             const int32_t* seq_cat, const float* backbone_cat,
                             int batch, int crop_len, int msa_depth,
                             int msa_len, double mutation_rate, uint64_t seed,
                             int num_workers, int queue_capacity,
                             int num_buckets, float min_dist, float max_dist,
                             int32_t ignore_index);
int af2_loader_next(void* handle, int32_t* seq, int32_t* msa, uint8_t* mask,
                    uint8_t* msa_mask, float* coords, float* backbone,
                    int32_t* labels);
int af2_loader_queue_size(void* handle);
void af2_loader_destroy(void* handle);
}

int main(int argc, char** argv) {
  const int rounds = argc > 1 ? std::atoi(argv[1]) : 3;
  const int B = 2, L = 16, M = 2, NM = 8;
  std::vector<int32_t> seq((size_t)B * L), msa((size_t)B * M * NM),
      labels((size_t)B * L * L);
  std::vector<uint8_t> mask((size_t)B * L), msa_mask((size_t)B * M * NM);
  std::vector<float> coords((size_t)B * L * 3), backbone((size_t)B * L * 9);

  for (int r = 0; r < rounds; ++r) {
    // 8 producers against a 1-slot admission window: every push contends
    void* ld = af2_loader_create(B, L, M, NM, 8, 42 + r, /*workers=*/8,
                                 /*capacity=*/1, 37, 2.0f, 20.0f, -100);
    for (int i = 0; i < 64; ++i) {
      if (af2_loader_next(ld, seq.data(), msa.data(), mask.data(),
                          msa_mask.data(), coords.data(), backbone.data(),
                          labels.data()) != 0) {
        std::fprintf(stderr, "round %d: loader stopped early at %d\n", r, i);
        return 1;
      }
    }
    if (af2_loader_queue_size(ld) < 0) return 1;
    // destroy with workers mid-flight (blocked producing or on admission)
    af2_loader_destroy(ld);
  }
  // destruction immediately after creation (workers may not have produced)
  for (int r = 0; r < rounds; ++r) {
    void* ld = af2_loader_create(B, L, M, NM, 8, r, 4, 2, 37, 2.0f, 20.0f,
                                 -100);
    af2_loader_destroy(ld);
  }

  // the real-data fill path under the same contention: two registered
  // chains (one shorter, one longer than the crop), 8 producers, 1-slot
  // window, mid-flight destruction
  {
    const int32_t lens[2] = {12, 24};
    std::vector<int32_t> seq_cat(12 + 24);
    std::vector<float> bb_cat((size_t)(12 + 24) * 9);
    for (size_t i = 0; i < seq_cat.size(); ++i) seq_cat[i] = (int32_t)(i % 20);
    for (size_t i = 0; i < bb_cat.size(); ++i) bb_cat[i] = 0.37f * (float)i;
    for (int r = 0; r < rounds; ++r) {
      void* ld = af2_real_loader_create(2, lens, seq_cat.data(), bb_cat.data(),
                                        B, L, M, NM, 0.15, 99 + r, 8, 1, 37,
                                        2.0f, 20.0f, -100);
      if (!ld) return 1;
      for (int i = 0; i < 64; ++i) {
        if (af2_loader_next(ld, seq.data(), msa.data(), mask.data(),
                            msa_mask.data(), coords.data(), backbone.data(),
                            labels.data()) != 0) {
          std::fprintf(stderr, "real round %d: stopped early at %d\n", r, i);
          return 1;
        }
      }
      af2_loader_destroy(ld);
    }
  }
  std::puts("loader_stress ok");
  return 0;
}
