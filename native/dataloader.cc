// Native host-side data-loader runtime for alphafold2_tpu.
//
// The reference crosses into native code for its data path through mdtraj's C
// PDB machinery and torch DataLoader workers (SURVEY.md S2.4); this is the
// TPU-framework equivalent: a C++ runtime that prepares fixed-shape training
// batches on host threads so the accelerator never waits on Python.
//
// Components (C ABI, consumed from Python via ctypes —
// alphafold2_tpu/data/native.py):
//   - af2_bucketize_distances: pairwise CA distance -> 37-bin distogram
//     labels with ignore_index masking (the label computation of
//     reference train_pre.py:75 / utils.py:33-38), O(N^2) on host.
//   - af2_synthesize_batch: deterministic synthetic chain batches (smoothed
//     3.8A random walk + N/C pseudo-backbone + mutated MSA rows), the
//     native twin of data/pipeline.py:SyntheticDataset.
//   - af2_loader_*: a multithreaded prefetching loader — worker threads
//     fill a bounded ring buffer of ready batches; the consumer pops
//     complete batches without holding the GIL (ctypes releases it during
//     the call). This is the "DataLoader worker" capability the reference
//     gets from torch, rebuilt for this framework's static-shape batches.
//
// Build: make -C native  ->  libaf2data.so. No dependencies beyond the C++17
// standard library and pthreads.

#include <atomic>
#include <cmath>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <queue>
#include <thread>
#include <vector>

extern "C" {

// ---------------------------------------------------------------------------
// distance bucketization (labels)
// ---------------------------------------------------------------------------

// coords: (n, 3) row-major float32; mask: (n,) uint8; out: (n, n) int32.
// Buckets span [min_dist, max_dist] with `num_buckets` thresholds; bin
// assignment is searchsorted-left over the first num_buckets-1 thresholds,
// masked pairs get ignore_index.
void af2_bucketize_distances(const float* coords, const uint8_t* mask, int n,
                             int num_buckets, float min_dist, float max_dist,
                             int32_t ignore_index, int32_t* out) {
  const float step = (max_dist - min_dist) / (float)(num_buckets - 1);
  for (int i = 0; i < n; ++i) {
    const float xi = coords[3 * i], yi = coords[3 * i + 1], zi = coords[3 * i + 2];
    for (int j = 0; j < n; ++j) {
      if (!mask[i] || !mask[j]) {
        out[(size_t)i * n + j] = ignore_index;
        continue;
      }
      const float dx = xi - coords[3 * j];
      const float dy = yi - coords[3 * j + 1];
      const float dz = zi - coords[3 * j + 2];
      const float d = std::sqrt(dx * dx + dy * dy + dz * dz);
      // searchsorted-left over thresholds min, min+step, ..., max (first
      // num_buckets-1 boundaries used, matching jnp/searchsorted semantics)
      int b = (int)std::ceil((d - min_dist) / step);
      if (d <= min_dist) b = 0;
      if (b > num_buckets - 1) b = num_buckets - 1;
      if (b < 0) b = 0;
      out[(size_t)i * n + j] = b;
    }
  }
}

// ---------------------------------------------------------------------------
// synthetic batch generation (native twin of SyntheticDataset)
// ---------------------------------------------------------------------------

namespace {

// splitmix64: deterministic, seedable, portable RNG
struct Rng {
  uint64_t s;
  explicit Rng(uint64_t seed) : s(seed) {}
  uint64_t next_u64() {
    uint64_t z = (s += 0x9e3779b97f4a7c15ull);
    z = (z ^ (z >> 30)) * 0xbf58476d1ce4e5b9ull;
    z = (z ^ (z >> 27)) * 0x94d049bb133111ebull;
    return z ^ (z >> 31);
  }
  // uniform in [0, 1)
  double uniform() { return (next_u64() >> 11) * (1.0 / 9007199254740992.0); }
  // integer in [0, m)
  uint64_t below(uint64_t m) { return next_u64() % m; }
  // standard normal (Box-Muller)
  double normal() {
    double u1 = uniform(), u2 = uniform();
    if (u1 < 1e-300) u1 = 1e-300;
    return std::sqrt(-2.0 * std::log(u1)) * std::cos(2.0 * M_PI * u2);
  }
};

constexpr int kPadIndex = 20;  // constants.AA_PAD_INDEX

// Shared MSA synthesis: M rows mutated from the (cropped) primary sequence
// at `rate`, masked to msa_len. Draw order (uniform, then conditional
// below) is part of the deterministic stream contract for both loaders.
void fill_msa_rows(Rng& rng, const int32_t* seq_row, int msa_len, double rate,
                   int M, int NM, int32_t* msa, uint8_t* msa_mask) {
  for (int m = 0; m < M; ++m) {
    int32_t* mrow = msa + (size_t)m * NM;
    uint8_t* mm = msa_mask + (size_t)m * NM;
    for (int i = 0; i < NM; ++i) {
      if (i < msa_len) {
        mrow[i] = rng.uniform() < rate ? (int32_t)rng.below(20) : seq_row[i];
        mm[i] = 1;
      } else {
        mrow[i] = kPadIndex;
      }
    }
  }
}

void smooth_walk(Rng& rng, int n, float* out /* (n,3) */) {
  // compact CA trace: ~3.8A steps with direction persistence, centered
  // (normalize the fresh step BEFORE the 0.6/0.4 blend, matching the numpy
  // twin data/pipeline.py:_smooth_walk)
  std::vector<double> step(3), prev(3, 0.0);
  double cx = 0, cy = 0, cz = 0;
  std::vector<double> acc(3 * (size_t)n, 0.0);
  double px = 0, py = 0, pz = 0;
  for (int i = 0; i < n; ++i) {
    for (int d = 0; d < 3; ++d) step[d] = rng.normal();
    double fresh_norm = std::sqrt(step[0] * step[0] + step[1] * step[1] +
                                  step[2] * step[2]) + 1e-9;
    for (int d = 0; d < 3; ++d) step[d] /= fresh_norm;
    if (i > 0)
      for (int d = 0; d < 3; ++d) step[d] = 0.6 * prev[d] + 0.4 * step[d];
    double norm = std::sqrt(step[0] * step[0] + step[1] * step[1] +
                            step[2] * step[2]) + 1e-9;
    for (int d = 0; d < 3; ++d) {
      step[d] /= norm;
      prev[d] = step[d];
    }
    px += 3.8 * step[0];
    py += 3.8 * step[1];
    pz += 3.8 * step[2];
    acc[3 * (size_t)i] = px;
    acc[3 * (size_t)i + 1] = py;
    acc[3 * (size_t)i + 2] = pz;
    cx += px; cy += py; cz += pz;
  }
  cx /= n; cy /= n; cz /= n;
  for (int i = 0; i < n; ++i) {
    out[3 * i] = (float)(acc[3 * (size_t)i] - cx);
    out[3 * i + 1] = (float)(acc[3 * (size_t)i + 1] - cy);
    out[3 * i + 2] = (float)(acc[3 * (size_t)i + 2] - cz);
  }
}

struct BatchSpec {
  int batch, crop_len, msa_depth, msa_len, min_len;
};

struct BatchBuffers {
  int32_t* seq;       // (B, L)
  int32_t* msa;       // (B, M, NM)
  uint8_t* mask;      // (B, L)
  uint8_t* msa_mask;  // (B, M, NM)
  float* coords;      // (B, L, 3)
  float* backbone;    // (B, L*3, 3)
};

void synthesize_into(const BatchSpec& spec, uint64_t seed, BatchBuffers buf) {
  const int B = spec.batch, L = spec.crop_len, M = spec.msa_depth,
            NM = spec.msa_len;
  Rng rng(seed);
  std::memset(buf.mask, 0, (size_t)B * L);
  std::memset(buf.msa_mask, 0, (size_t)B * M * NM);
  std::memset(buf.coords, 0, (size_t)B * L * 3 * sizeof(float));
  std::memset(buf.backbone, 0, (size_t)B * L * 9 * sizeof(float));
  std::vector<float> ca((size_t)L * 3);
  // clamp so crop_len < min_len cannot underflow the modulus (the numpy
  // twin raises for that config; here the chain just fills the crop)
  const int min_len = spec.min_len > L ? L : (spec.min_len < 1 ? 1 : spec.min_len);
  for (int b = 0; b < B; ++b) {
    const int true_len = min_len + (int)rng.below((uint64_t)(L - min_len + 1));
    int32_t* seq_row = buf.seq + (size_t)b * L;
    for (int i = 0; i < L; ++i)
      seq_row[i] = i < true_len ? (int32_t)rng.below(20) : kPadIndex;
    for (int i = 0; i < true_len; ++i) buf.mask[(size_t)b * L + i] = 1;

    smooth_walk(rng, true_len, ca.data());
    float* crow = buf.coords + (size_t)b * L * 3;
    std::memcpy(crow, ca.data(), (size_t)true_len * 3 * sizeof(float));

    // backbone: N and C pseudo-atoms ~1.5A off each CA along the chain
    float* bb = buf.backbone + (size_t)b * L * 9;
    for (int i = 0; i < true_len; ++i) {
      float dx, dy, dz;
      if (i == 0 && true_len > 1) {
        dx = ca[3] - ca[0]; dy = ca[4] - ca[1]; dz = ca[5] - ca[2];
      } else if (i > 0) {
        dx = ca[3 * i] - ca[3 * (i - 1)];
        dy = ca[3 * i + 1] - ca[3 * (i - 1) + 1];
        dz = ca[3 * i + 2] - ca[3 * (i - 1) + 2];
      } else {
        dx = 1; dy = 0; dz = 0;
      }
      const float nrm = std::sqrt(dx * dx + dy * dy + dz * dz) + 1e-9f;
      dx /= nrm; dy /= nrm; dz /= nrm;
      const float jx = (float)(0.1 * rng.normal());
      const float jy = (float)(0.1 * rng.normal());
      const float jz = (float)(0.1 * rng.normal());
      float* res = bb + (size_t)i * 9;
      res[0] = ca[3 * i] - 1.46f * dx + jx;       // N
      res[1] = ca[3 * i + 1] - 1.46f * dy + jy;
      res[2] = ca[3 * i + 2] - 1.46f * dz + jz;
      res[3] = ca[3 * i];                          // CA
      res[4] = ca[3 * i + 1];
      res[5] = ca[3 * i + 2];
      res[6] = ca[3 * i] + 1.52f * dx - jx;        // C
      res[7] = ca[3 * i + 1] + 1.52f * dy - jy;
      res[8] = ca[3 * i + 2] + 1.52f * dz - jz;
    }

    // MSA rows: mutate the primary sequence at rate 0.15
    const int msa_len = true_len < NM ? true_len : NM;
    fill_msa_rows(rng, seq_row, msa_len, 0.15, M, NM,
                  buf.msa + (size_t)b * M * NM,
                  buf.msa_mask + (size_t)b * M * NM);
  }
}

}  // namespace

// One-shot synthesis into caller-allocated buffers (deterministic by seed).
void af2_synthesize_batch(int batch, int crop_len, int msa_depth, int msa_len,
                          int min_len, uint64_t seed, int32_t* seq,
                          int32_t* msa, uint8_t* mask, uint8_t* msa_mask,
                          float* coords, float* backbone) {
  BatchSpec spec{batch, crop_len, msa_depth, msa_len, min_len};
  BatchBuffers buf{seq, msa, mask, msa_mask, coords, backbone};
  synthesize_into(spec, seed, buf);
}

// ---------------------------------------------------------------------------
// multithreaded prefetching loader
// ---------------------------------------------------------------------------

namespace {

struct OwnedBatch {
  uint64_t index;  // sequential batch number; consumers pop in index order
  std::vector<int32_t> seq, msa;
  std::vector<uint8_t> mask, msa_mask;
  std::vector<float> coords, backbone;
  std::vector<int32_t> labels;  // (B, L, L) distogram labels
};

// Loader-owned copy of one real chain (seq tokens + N/CA/C backbone).
struct Chain {
  std::vector<int32_t> seq;
  std::vector<float> backbone;  // (len, 3, 3) row-major
};

// Crop/pad/assemble one batch from registered real chains — the native twin
// of data/pipeline.py:NpzShardDataset's per-item logic (random crop window,
// prefix masks, MSA synthesized by mutating the cropped sequence). Chain
// choice is uniform per sample (seeded), not epoch-shuffled: the stream is
// deterministic in (seed, index) for any worker count.
void fill_from_chains(const std::vector<Chain>& chains, const BatchSpec& spec,
                      double mutation_rate, uint64_t seed, BatchBuffers buf) {
  const int B = spec.batch, L = spec.crop_len, M = spec.msa_depth,
            NM = spec.msa_len;
  Rng rng(seed);
  std::memset(buf.mask, 0, (size_t)B * L);
  std::memset(buf.msa_mask, 0, (size_t)B * M * NM);
  std::memset(buf.coords, 0, (size_t)B * L * 3 * sizeof(float));
  std::memset(buf.backbone, 0, (size_t)B * L * 9 * sizeof(float));
  for (int b = 0; b < B; ++b) {
    const Chain& c = chains[rng.below(chains.size())];
    const int len = (int)c.seq.size();
    const int start = len > L ? (int)rng.below((uint64_t)(len - L + 1)) : 0;
    const int w = len < L ? len : L;
    int32_t* seq_row = buf.seq + (size_t)b * L;
    for (int i = 0; i < L; ++i)
      seq_row[i] = i < w ? c.seq[(size_t)start + i] : kPadIndex;
    for (int i = 0; i < w; ++i) buf.mask[(size_t)b * L + i] = 1;
    float* crow = buf.coords + (size_t)b * L * 3;
    float* bb = buf.backbone + (size_t)b * L * 9;
    for (int i = 0; i < w; ++i) {
      const float* res = c.backbone.data() + (size_t)(start + i) * 9;
      std::memcpy(bb + (size_t)i * 9, res, 9 * sizeof(float));
      std::memcpy(crow + (size_t)i * 3, res + 3, 3 * sizeof(float));  // CA
    }
    const int msa_len = w < NM ? w : NM;
    fill_msa_rows(rng, seq_row, msa_len, mutation_rate, M, NM,
                  buf.msa + (size_t)b * M * NM,
                  buf.msa_mask + (size_t)b * M * NM);
  }
}

struct BatchOrder {
  bool operator()(const OwnedBatch* a, const OwnedBatch* b) const {
    return a->index > b->index;  // min-heap on index
  }
};

struct Loader {
  BatchSpec spec;
  uint64_t base_seed;
  int num_buckets;
  float min_dist, max_dist;
  int32_t ignore_index;
  std::vector<Chain> chains;     // non-empty => real-data mode
  double mutation_rate = 0.15;   // MSA synthesis rate (real-data mode)

  std::vector<std::thread> workers;
  // Min-heap keyed by batch index + a consume cursor: workers claim indices
  // atomically but may finish out of order; the consumer waits for the
  // exact next index, so the batch STREAM is deterministic for a given
  // seed regardless of worker count or scheduling.
  std::priority_queue<OwnedBatch*, std::vector<OwnedBatch*>, BatchOrder> ready;
  std::mutex mu;
  std::condition_variable not_empty, not_full;
  size_t capacity;
  std::atomic<uint64_t> next_index{0};
  uint64_t next_consume = 0;
  std::atomic<bool> stop{false};

  void worker_loop() {
    const int B = spec.batch, L = spec.crop_len, M = spec.msa_depth,
              NM = spec.msa_len;
    while (!stop.load(std::memory_order_relaxed)) {
      auto* ob = new OwnedBatch();
      ob->seq.resize((size_t)B * L);
      ob->msa.resize((size_t)B * M * NM);
      ob->mask.resize((size_t)B * L);
      ob->msa_mask.resize((size_t)B * M * NM);
      ob->coords.resize((size_t)B * L * 3);
      ob->backbone.resize((size_t)B * L * 9);
      ob->labels.resize((size_t)B * L * L);
      ob->index = next_index.fetch_add(1, std::memory_order_relaxed);
      BatchBuffers buf{ob->seq.data(), ob->msa.data(), ob->mask.data(),
                       ob->msa_mask.data(), ob->coords.data(),
                       ob->backbone.data()};
      if (chains.empty())
        synthesize_into(spec, base_seed + ob->index, buf);
      else
        fill_from_chains(chains, spec, mutation_rate, base_seed + ob->index,
                         buf);
      for (int b = 0; b < B; ++b)
        af2_bucketize_distances(ob->coords.data() + (size_t)b * L * 3,
                                ob->mask.data() + (size_t)b * L, L,
                                num_buckets, min_dist, max_dist, ignore_index,
                                ob->labels.data() + (size_t)b * L * L);
      std::unique_lock<std::mutex> lock(mu);
      // window-based flow control: admit only indices within `capacity` of
      // the consume cursor. A plain size bound would deadlock: the heap
      // could fill with later indices while the worker holding the exact
      // next one waits for space.
      not_full.wait(lock, [this, ob] {
        return ob->index < next_consume + capacity || stop.load();
      });
      if (stop.load()) {
        delete ob;
        return;
      }
      ready.push(ob);
      not_empty.notify_all();
    }
  }
};

}  // namespace

namespace {

// Shared init tail: label-bucketization params, queue window, worker spawn.
// ld->spec (and chains/mutation_rate for real-data mode) must be set first.
void* loader_start(Loader* ld, uint64_t seed, int num_workers,
                   int queue_capacity, int num_buckets, float min_dist,
                   float max_dist, int32_t ignore_index) {
  ld->base_seed = seed;
  ld->num_buckets = num_buckets;
  ld->min_dist = min_dist;
  ld->max_dist = max_dist;
  ld->ignore_index = ignore_index;
  ld->capacity = queue_capacity > 0 ? (size_t)queue_capacity : 4;
  if (num_workers < 1) num_workers = 1;
  for (int i = 0; i < num_workers; ++i)
    ld->workers.emplace_back([ld] { ld->worker_loop(); });
  return ld;
}

}  // namespace

void* af2_loader_create(int batch, int crop_len, int msa_depth, int msa_len,
                        int min_len, uint64_t seed, int num_workers,
                        int queue_capacity, int num_buckets, float min_dist,
                        float max_dist, int32_t ignore_index) {
  auto* ld = new Loader();
  ld->spec = BatchSpec{batch, crop_len, msa_depth, msa_len, min_len};
  return loader_start(ld, seed, num_workers, queue_capacity, num_buckets,
                      min_dist, max_dist, ignore_index);
}

// Real-data prefetching loader: same worker/ring machinery, but batches are
// cropped/padded from registered chains instead of synthesized. Chains are
// passed concatenated (seq_cat: sum(lens) int32 tokens; backbone_cat:
// sum(lens)*9 floats, (len, 3, 3) N/CA/C per chain) and COPIED — the caller
// may free its buffers after this returns. Returns NULL when n_chains < 1
// or any length < 1.
void* af2_real_loader_create(int n_chains, const int32_t* lens,
                             const int32_t* seq_cat, const float* backbone_cat,
                             int batch, int crop_len, int msa_depth,
                             int msa_len, double mutation_rate, uint64_t seed,
                             int num_workers, int queue_capacity,
                             int num_buckets, float min_dist, float max_dist,
                             int32_t ignore_index) {
  if (n_chains < 1) return nullptr;
  auto* ld = new Loader();
  size_t off = 0;
  for (int c = 0; c < n_chains; ++c) {
    const int len = lens[c];
    if (len < 1) {
      delete ld;
      return nullptr;
    }
    Chain ch;
    ch.seq.assign(seq_cat + off, seq_cat + off + len);
    ch.backbone.assign(backbone_cat + off * 9,
                       backbone_cat + (off + len) * 9);
    ld->chains.push_back(std::move(ch));
    off += (size_t)len;
  }
  ld->spec = BatchSpec{batch, crop_len, msa_depth, msa_len, /*min_len=*/1};
  ld->mutation_rate = mutation_rate;
  return loader_start(ld, seed, num_workers, queue_capacity, num_buckets,
                      min_dist, max_dist, ignore_index);
}

// Blocks until a batch is ready, then copies it into the caller's buffers.
// Returns 0 on success, -1 if the loader is stopped.
int af2_loader_next(void* handle, int32_t* seq, int32_t* msa, uint8_t* mask,
                    uint8_t* msa_mask, float* coords, float* backbone,
                    int32_t* labels) {
  auto* ld = (Loader*)handle;
  if (ld == nullptr) return -1;
  OwnedBatch* ob = nullptr;
  {
    std::unique_lock<std::mutex> lock(ld->mu);
    ld->not_empty.wait(lock, [ld] {
      return (!ld->ready.empty() && ld->ready.top()->index == ld->next_consume)
             || ld->stop.load();
    });
    if (ld->stop.load()) return -1;
    ob = ld->ready.top();
    ld->ready.pop();
    ld->next_consume++;
    ld->not_full.notify_all();  // window advanced: several may now be admitted
  }
  std::memcpy(seq, ob->seq.data(), ob->seq.size() * sizeof(int32_t));
  std::memcpy(msa, ob->msa.data(), ob->msa.size() * sizeof(int32_t));
  std::memcpy(mask, ob->mask.data(), ob->mask.size());
  std::memcpy(msa_mask, ob->msa_mask.data(), ob->msa_mask.size());
  std::memcpy(coords, ob->coords.data(), ob->coords.size() * sizeof(float));
  std::memcpy(backbone, ob->backbone.data(),
              ob->backbone.size() * sizeof(float));
  if (labels)
    std::memcpy(labels, ob->labels.data(), ob->labels.size() * sizeof(int32_t));
  delete ob;
  return 0;
}

int af2_loader_queue_size(void* handle) {
  auto* ld = (Loader*)handle;
  if (ld == nullptr) return -1;
  std::lock_guard<std::mutex> lock(ld->mu);
  return (int)ld->ready.size();
}

void af2_loader_destroy(void* handle) {
  auto* ld = (Loader*)handle;
  if (ld == nullptr) return;
  {
    std::lock_guard<std::mutex> lock(ld->mu);
    ld->stop.store(true);
  }
  ld->not_empty.notify_all();
  ld->not_full.notify_all();
  for (auto& t : ld->workers) t.join();
  while (!ld->ready.empty()) {
    delete ld->ready.top();
    ld->ready.pop();
  }
  delete ld;
}

}  // extern "C"
